"""The replica boundary: one uniform handle protocol over a
SamplingService, in-process or across HTTP.

The router (serve/router.py) never touches a service directly — it
talks to a *replica handle*:

    name                        stable fleet identity
    healthz() -> dict           service.health_snapshot() + watcher
                                breaker state (may raise
                                ReplicaUnreachable)
    submit(cond, **kw)          -> ticket with .result(timeout)
    submit_trajectory(cond, poses, **kw) -> ticket with .result(timeout)
    begin_drain() / drain(t)    PR 11 drain state machine
    poke()                      registry watcher: poll NOW
    metrics_text() -> str       Prometheus exposition for aggregation
    close()

`LocalReplica` wraps an in-process service (tier-1 tests; no ports).
`ReplicaServer` + `HttpReplica` carry the SAME protocol across a
process boundary for the real fleet (`nvs3d route`, serve_bench
--fleet): the structured error contract (Rejected/SampleAnomaly/
TrajectoryExpired with retryable/retry_after_s/partial frames) is
marshalled losslessly, so the router's failover logic is transport-
blind. A transport-level failure (connection refused, socket timeout,
torn response — the replica DIED, it didn't answer) surfaces as
`ReplicaUnreachable`, which is retryable by construction: the request
never entered a queue, so resubmitting elsewhere cannot double-serve.
"""

from __future__ import annotations

import base64
import http.client
import io
import json
import socket
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from novel_view_synthesis_3d_tpu import obs
from novel_view_synthesis_3d_tpu.sample.service import (
    DeadlineExceeded,
    Rejected,
    SampleAnomaly,
    ServeError,
    TrajectoryExpired,
)


class ReplicaUnreachable(ServeError):
    """Transport-level replica failure: died, unreachable, or answered
    with a torn/non-protocol response. Retryable against a peer — the
    request provably never committed to the dead replica's queue."""

    retryable = True
    retry_after_s = 0.0


# ---------------------------------------------------------------------------
# Wire marshalling (arrays + the structured error contract)
# ---------------------------------------------------------------------------
def encode_array(arr: np.ndarray) -> str:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
    return base64.b64encode(buf.getvalue()).decode("ascii")


def decode_array(text: str) -> np.ndarray:
    return np.load(io.BytesIO(base64.b64decode(text)),
                   allow_pickle=False)


def error_to_wire(exc: BaseException) -> dict:
    """Structured serving error → JSON-able dict. Partial trajectory
    frames (SampleAnomaly / TrajectoryExpired) ride along stacked, so
    the router can stitch a failover continuation."""
    wire = {
        "type": type(exc).__name__,
        "message": str(exc),
        "retryable": bool(getattr(exc, "retryable", False)),
        "retry_after_s": float(getattr(exc, "retry_after_s", 0.0) or 0.0),
    }
    frames = getattr(exc, "frames", None)
    if frames:
        wire["frames"] = encode_array(np.stack(frames))
    if hasattr(exc, "frame_index"):
        wire["frame_index"] = int(exc.frame_index)
    return wire


def wire_to_error(wire: dict) -> ServeError:
    """Inverse of error_to_wire: re-raise the SAME exception class the
    in-process service would have raised, so router failover logic and
    sample/client.submit_with_retry see one contract either way."""
    msg = str(wire.get("message", ""))
    frames = wire.get("frames")
    frame_list = [f for f in decode_array(frames)] if frames else []
    etype = wire.get("type")
    if etype == "SampleAnomaly":
        return SampleAnomaly(
            msg, frames=frame_list,
            frame_index=int(wire.get("frame_index", 0)),
            retry_after_s=float(wire.get("retry_after_s", 0.0)))
    if etype == "TrajectoryExpired":
        return TrajectoryExpired(
            msg, frames=frame_list,
            frame_index=int(wire.get("frame_index", 0)))
    if etype == "DeadlineExceeded":
        return DeadlineExceeded(msg)
    if etype == "Rejected":
        return Rejected(
            msg, retryable=bool(wire.get("retryable", False)),
            retry_after_s=float(wire.get("retry_after_s", 0.0)))
    if etype == "ReplicaUnreachable":
        # A replica that answers "I am closed" over a still-warm
        # keepalive socket is dead for routing purposes — same class
        # as a connection that never opened.
        return ReplicaUnreachable(msg)
    err = ServeError(msg or f"replica error ({etype})")
    err.retryable = bool(wire.get("retryable", False))
    err.retry_after_s = float(wire.get("retry_after_s", 0.0))
    return err


def replica_health(service, watcher=None) -> dict:
    """The fleet /healthz body: the service's own snapshot (step_debt,
    brownout_level, serve_state, ...) plus the registry watcher's
    circuit-breaker state — the two inputs the router's dispatch policy
    and the rolling-deploy gate read."""
    snap = service.health_snapshot()
    if watcher is not None:
        snap["breaker"] = watcher.breaker_state
        snap["swaps"] = int(watcher.swaps)
        snap["swap_failures"] = int(watcher.failures)
    return snap


# ---------------------------------------------------------------------------
# In-process replica (tier-1 tests, single-host fleets)
# ---------------------------------------------------------------------------
class LocalReplica:
    """Handle over an in-process SamplingService (+ optional watcher).

    `run_dir` names the replica's telemetry folder so fleet trace
    reconstruction (obs/reqtrace.load_fleet_rows) can find its rows."""

    def __init__(self, name: str, service, *, watcher=None,
                 run_dir: str = ""):
        self.name = str(name)
        self.service = service
        self.watcher = watcher
        self.run_dir = run_dir or service.serve.results_folder

    def healthz(self) -> dict:
        if self.service is None:
            raise ReplicaUnreachable(f"replica {self.name} closed")
        return replica_health(self.service, self.watcher)

    def submit(self, cond, **kw):
        if self.service is None:
            raise ReplicaUnreachable(f"replica {self.name} closed")
        return self.service.submit(cond, **kw)

    def submit_trajectory(self, cond, poses, **kw):
        if self.service is None:
            raise ReplicaUnreachable(f"replica {self.name} closed")
        return self.service.submit_trajectory(cond, poses=poses, **kw)

    def begin_drain(self) -> None:
        if self.service is not None:
            self.service.begin_drain()

    def drain(self, timeout_s: Optional[float] = None) -> None:
        if self.service is not None:
            self.service.drain(timeout_s)

    def poke(self) -> None:
        if self.watcher is not None:
            self.watcher.poke()

    def metrics_text(self) -> str:
        return obs.get_registry().render_prometheus()

    def close(self) -> None:
        svc, self.service = self.service, None
        if self.watcher is not None:
            self.watcher.stop()
        if svc is not None:
            svc.stop()


# ---------------------------------------------------------------------------
# HTTP transport (subprocess fleets)
# ---------------------------------------------------------------------------
class _ReplicaHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "nvs3d-replica"

    def log_message(self, fmt, *args):  # stdlib default logs to stderr
        pass

    # -- helpers -------------------------------------------------------
    def _json(self, code: int, obj: dict) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, exc: BaseException) -> None:
        wire = error_to_wire(exc)
        code = 503 if wire["retryable"] else (
            504 if isinstance(exc, DeadlineExceeded) else 400)
        self._json(code, {"error": wire})

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b""
        return json.loads(raw.decode()) if raw else {}

    # -- routes --------------------------------------------------------
    def do_GET(self):
        core = self.server.core
        if self.path.startswith("/healthz"):
            try:
                self._json(200, core.healthz())
            except Exception as e:
                self._json(500, {"error": error_to_wire(e)})
        elif self.path.startswith("/metrics"):
            body = core.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._json(404, {"error": {"type": "ServeError",
                                       "message": "unknown path"}})

    def do_POST(self):
        core = self.server.core
        try:
            req = self._body()
        except ValueError:
            self._json(400, {"error": {"type": "Rejected",
                                       "message": "bad request json",
                                       "retryable": False}})
            return
        try:
            if self.path.startswith("/submit_trajectory"):
                self._handle_traj(core, req)
            elif self.path.startswith("/submit"):
                self._handle_submit(core, req)
            elif self.path.startswith("/drain"):
                core.begin_drain()
                if req.get("full"):
                    core.drain(req.get("timeout_s"))
                self._json(200, core.healthz())
            elif self.path.startswith("/poke"):
                core.poke()
                self._json(200, {"ok": True})
            else:
                self._json(404, {"error": {"type": "ServeError",
                                           "message": "unknown path"}})
        except ServeError as e:
            self._error(e)
        except Exception as e:  # pragma: no cover - defensive
            self._json(500, {"error": {"type": "ServeError",
                                       "message": repr(e)}})

    def _kwargs(self, req: dict) -> dict:
        kw = {}
        # "session" only matters when the core is a router ingress
        # (serve/router_main.py) — a replica-bound call never sets it.
        for key in ("seed", "sample_steps", "guidance_weight",
                    "deadline_ms", "k_max", "trace_id", "session"):
            if req.get(key) is not None:
                kw[key] = req[key]
        if "seed" in kw:
            kw["seed"] = int(kw["seed"])
        return kw

    def _handle_submit(self, core, req: dict) -> None:
        cond = {k: decode_array(v) for k, v in req["cond"].items()}
        kw = self._kwargs(req)
        kw.pop("k_max", None)
        ticket = core.submit(cond, **kw)
        img = ticket.result(timeout=float(req.get("timeout_s") or 600.0))
        self._json(200, {
            "image": encode_array(img),
            "request_id": int(ticket.request_id),
            "model_version": ticket.model_version,
        })

    def _handle_traj(self, core, req: dict) -> None:
        cond = {k: decode_array(v) for k, v in req["cond"].items()}
        poses = {"R2": decode_array(req["poses"]["R2"]),
                 "t2": decode_array(req["poses"]["t2"])}
        ticket = core.submit_trajectory(cond, poses, **self._kwargs(req))
        frames = ticket.result(
            timeout=float(req.get("timeout_s") or 600.0))
        self._json(200, {
            "frames": encode_array(frames),
            "request_id": int(ticket.request_id),
            "model_version": ticket.model_version,
        })


class ReplicaServer:
    """HTTP face of one replica: /submit, /submit_trajectory, /drain,
    /poke, /healthz, /metrics over a stdlib ThreadingHTTPServer bound
    to loopback (same trust model as obs.MetricsServer — a fleet
    fabric, not an internet-facing endpoint)."""

    def __init__(self, core, *, host: str = "127.0.0.1", port: int = 0):
        self.core = core  # a LocalReplica (or anything handle-shaped)
        self._httpd = ThreadingHTTPServer((host, port), _ReplicaHandler)
        self._httpd.daemon_threads = True
        self._httpd.core = core
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"replica-http-{core.name}")
        self._thread.start()

    def url(self, path: str = "") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10.0)


class _HttpTicket:
    """Client-side ticket over one in-flight HTTP request. The POST runs
    on its own thread from construction (submission is not deferred to
    result()), mirroring the in-process ticket's semantics."""

    def __init__(self, call):
        self.request_id = -1
        self.model_version = ""
        self._result = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()

        def run():
            try:
                self._result = call(self)
            except BaseException as e:
                self._error = e
            self._done.set()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("replica call still in flight")
        if self._error is not None:
            raise self._error
        return self._result


class HttpReplica:
    """Handle over a replica process at `base_url` (ReplicaServer /
    serve/replica_main.py). `run_dir` (optional) names the replica's
    telemetry folder on shared storage for fleet trace reconstruction.
    """

    def __init__(self, name: str, base_url: str, *, run_dir: str = "",
                 health_timeout_s: float = 3.0,
                 submit_timeout_s: float = 600.0,
                 connect_timeout_s: float = 3.0):
        self.name = str(name)
        self.base_url = base_url.rstrip("/")
        self.run_dir = run_dir
        self.health_timeout_s = float(health_timeout_s)
        self.submit_timeout_s = float(submit_timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        parsed = urllib.parse.urlsplit(self.base_url)
        self._host = parsed.hostname or "127.0.0.1"
        self._port = parsed.port or 80
        self._path_prefix = parsed.path.rstrip("/")
        self._local = threading.local()  # per-thread keepalive conn

    # -- plumbing ------------------------------------------------------
    def _connect(self) -> http.client.HTTPConnection:
        conn = http.client.HTTPConnection(
            self._host, self._port, timeout=self.connect_timeout_s)
        try:
            conn.connect()
        except (ConnectionError, socket.timeout, TimeoutError,
                OSError) as e:
            conn.close()
            raise ReplicaUnreachable(
                f"replica {self.name} unreachable at "
                f"{self.base_url}: {e}") from e
        return conn

    def _raw(self, method: str, path: str, body: Optional[bytes],
             timeout_s: float):
        """One HTTP exchange over a per-thread keepalive connection,
        returning ``(status, body_bytes)``.

        The connect and read phases run under SEPARATE timeouts: a dead
        host must fail fast (``connect_timeout_s``, seconds) even when
        the call is a long-poll submit whose read budget is minutes —
        folding both into one timeout either hangs health probes on
        SYN blackholes or truncates legitimate sampling waits.

        A send/response failure on a REUSED connection is retried
        exactly once on a fresh socket: the replica's HTTP server may
        have closed the idle keepalive socket between calls, and that
        reset says nothing about replica health. A FRESH connection
        that fails is never retried here — that is real unreachability
        and the router's failover owns it."""
        headers = {"Content-Type": "application/json"} if body else {}
        for fresh_retry in (False, True):
            conn = getattr(self._local, "conn", None)
            reused = conn is not None
            if conn is None:
                conn = self._connect()
            self._local.conn = None  # never share a conn mid-flight
            try:
                conn.sock.settimeout(timeout_s)
                conn.request(method, self._path_prefix + path,
                             body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
            except (http.client.RemoteDisconnected,
                    ConnectionResetError, BrokenPipeError) as e:
                conn.close()
                if reused and not fresh_retry:
                    continue  # stale keepalive socket: retry once fresh
                raise ReplicaUnreachable(
                    f"replica {self.name}: connection reset at "
                    f"{path}: {e}") from e
            except (http.client.HTTPException, ConnectionError,
                    socket.timeout, TimeoutError, OSError) as e:
                conn.close()
                raise ReplicaUnreachable(
                    f"replica {self.name} unreachable at "
                    f"{self.base_url}{path}: {e}") from e
            if resp.will_close:
                conn.close()
            else:
                self._local.conn = conn
            return resp.status, data
        raise AssertionError("unreachable")  # pragma: no cover

    def _call(self, path: str, payload: Optional[dict],
              timeout_s: float) -> dict:
        body = None if payload is None else json.dumps(payload).encode()
        status, data = self._raw(
            "POST" if body is not None else "GET", path, body, timeout_s)
        try:
            obj = json.loads(data.decode())
        except ValueError:
            raise ReplicaUnreachable(
                f"replica {self.name}: torn response "
                f"(HTTP {status})") from None
        if status >= 400:
            raise wire_to_error(obj.get("error") or {}) from None
        return obj

    # -- handle protocol ----------------------------------------------
    def healthz(self) -> dict:
        return self._call("/healthz", None, self.health_timeout_s)

    def submit(self, cond, *, seed: int = 0, sample_steps=None,
               guidance_weight=None, deadline_ms=None, trace_id=None,
               session=None, timeout_s: Optional[float] = None):
        payload = {
            "cond": {k: encode_array(v) for k, v in cond.items()},
            "seed": int(seed), "sample_steps": sample_steps,
            "guidance_weight": guidance_weight,
            "deadline_ms": deadline_ms, "trace_id": trace_id,
            "session": session,
            "timeout_s": timeout_s or self.submit_timeout_s,
        }

        def call(ticket):
            resp = self._call("/submit", payload,
                              (timeout_s or self.submit_timeout_s) + 30.0)
            ticket.request_id = int(resp.get("request_id", -1))
            ticket.model_version = resp.get("model_version", "")
            return decode_array(resp["image"])

        return _HttpTicket(call)

    def submit_trajectory(self, cond, poses, *, seed: int = 0,
                          sample_steps=None, guidance_weight=None,
                          deadline_ms=None, k_max=None, trace_id=None,
                          session=None,
                          timeout_s: Optional[float] = None):
        if not isinstance(poses, dict):
            arr = np.asarray(poses, np.float32)
            poses = {"R2": arr[:, :3, :3], "t2": arr[:, :3, 3]}
        payload = {
            "cond": {k: encode_array(v) for k, v in cond.items()},
            "poses": {"R2": encode_array(poses["R2"]),
                      "t2": encode_array(poses["t2"])},
            "seed": int(seed), "sample_steps": sample_steps,
            "guidance_weight": guidance_weight,
            "deadline_ms": deadline_ms, "k_max": k_max,
            "trace_id": trace_id, "session": session,
            "timeout_s": timeout_s or self.submit_timeout_s,
        }

        def call(ticket):
            resp = self._call("/submit_trajectory", payload,
                              (timeout_s or self.submit_timeout_s) + 30.0)
            ticket.request_id = int(resp.get("request_id", -1))
            ticket.model_version = resp.get("model_version", "")
            return decode_array(resp["frames"])

        return _HttpTicket(call)

    def begin_drain(self) -> None:
        self._call("/drain", {"full": False}, self.health_timeout_s)

    def drain(self, timeout_s: Optional[float] = None) -> None:
        self._call("/drain", {"full": True, "timeout_s": timeout_s},
                   (timeout_s or 60.0) + 30.0)

    def poke(self) -> None:
        self._call("/poke", {}, self.health_timeout_s)

    def metrics_text(self) -> str:
        status, data = self._raw("GET", "/metrics", None,
                                 self.health_timeout_s)
        if status != 200:
            raise ReplicaUnreachable(
                f"replica {self.name}: /metrics HTTP {status}")
        return data.decode()

    def close(self) -> None:
        # The replica PROCESS has its own lifecycle (SIGTERM → drain);
        # only this thread's pooled keepalive socket is ours to drop.
        conn = getattr(self._local, "conn", None)
        self._local.conn = None
        if conn is not None:
            conn.close()
