"""Zero-downtime rolling deploys over the fleet (`nvs3d route deploy`).

Composition, not new machinery: the deploy driver scripts four
subsystems that already exist —

  router.quiesce/await_idle/readmit   traffic control (PR 16)
  registry channel + watcher.poke()   the swap itself (PR 5): the
                                      channel pointer moves ONCE, then
                                      each replica is poked one at a
                                      time, so the watcher fleet rolls
                                      instead of thundering
  /healthz breaker field              swap health (PR 11 circuit
                                      breaker, exported per satellite):
                                      an open breaker means verify/
                                      stage FAILED on that replica
  /healthz slo_fast_burn              the promotion gate (PR 14 burn
                                      rate): a canary serving garbage
                                      burns error budget fast and is
                                      caught during probation

Per replica, in stable (sorted) order:

  gate      breaker must be closed BEFORE we touch the replica — a
            replica already failing swaps is not a deploy target
  quiesce   out of rotation; router re-pins orbit sessions elsewhere
  drain     await queue_depth==0 AND step_debt==0 (bounded by
            router.deploy_drain_timeout_s) — the replica is idle, so
            the swap cannot race in-flight work (the service would
            tolerate it; the deploy is just stricter)
  swap      poke the watcher, await healthz model_version == target
            (deploy_swap_timeout_s); a breaker that opens here means
            the artifact failed verify/stage on this replica
  readmit   back into rotation
  probation deploy_probation_s of live traffic: fail if the breaker
            leaves closed, slo_breached flips true, or slo_fast_burn
            crosses deploy_burn_max

Any gate failure triggers AUTO-ROLLBACK: the channel pointer is rolled
back (store.rollback), every replica that already swapped is quiesced,
poked back to the prior version, and readmitted — the fleet converges
on the pre-deploy version and the report says so. Throughout, N-1
replicas keep serving: zero downtime is asserted (not assumed) by the
serve_bench --fleet rolling-deploy lane, which keeps a closed-loop
client running across the whole deploy and requires zero failures.
"""

from __future__ import annotations

import time
from typing import List, Optional

from novel_view_synthesis_3d_tpu.config import RouterConfig


def _health(router, name: str) -> dict:
    try:
        return router._states[name].handle.healthz()
    except Exception:
        return {}


def _await_version(router, name: str, version: str, timeout_s: float,
                   sleep, clock, poll_s: float = 0.05) -> bool:
    deadline = clock() + timeout_s
    while clock() < deadline:
        snap = _health(router, name)
        if snap.get("model_version") == version:
            return True
        # A breaker that opens during the wait means the swap FAILED
        # (verify/stage error) — waiting out the timeout is pointless.
        if snap.get("breaker") == "open":
            return False
        sleep(poll_s)
    return False


def rolling_deploy(router, store, channel: str, target_version: str, *,
                   rcfg: Optional[RouterConfig] = None, bus=None,
                   clock=time.monotonic, sleep=time.sleep,
                   replicas: Optional[List[str]] = None) -> dict:
    """Roll `target_version` across the fleet one replica at a time.

    Returns a report dict: {"status": "deployed" | "rolled_back" |
    "refused", "target", "previous", "steps": [per-replica records],
    "reason"}. Never raises for gate failures — the report is the
    contract (`nvs3d route deploy` exits nonzero on != deployed)."""
    rcfg = rcfg or getattr(router, "rcfg", None) or RouterConfig()

    def event(kind: str, detail: str) -> None:
        if bus is not None:
            bus.event(0, kind, detail, model_version=target_version,
                      echo="[deploy]")

    names = sorted(replicas if replicas is not None
                   else router._states.keys())
    previous = store.read_channel(channel)
    report = {"status": "deployed", "target": target_version,
              "previous": previous, "channel": channel, "steps": [],
              "reason": ""}

    # Fleet pre-gate: refuse outright (no channel move, nothing to roll
    # back) if any target replica is unreachable or breaker-open.
    for name in names:
        snap = _health(router, name)
        if not snap:
            report.update(status="refused",
                          reason=f"replica {name} unreachable")
            event("deploy_refused", report["reason"])
            return report
        if snap.get("breaker", "closed") != "closed":
            report.update(
                status="refused",
                reason=f"replica {name} swap breaker is "
                       f"{snap['breaker']} — heal or roll the channel "
                       "before deploying")
            event("deploy_refused", report["reason"])
            return report

    event("deploy_begin",
          f"channel {channel}: {previous or '<unset>'} -> "
          f"{target_version} across {len(names)} replica(s)")
    store.set_channel(channel, target_version)
    swapped: List[str] = []

    def rollback(reason: str) -> dict:
        event("deploy_rollback", f"rolling back: {reason}")
        try:
            restored = store.rollback(channel)
        except Exception:
            # History exhausted (fresh registry): restore directly.
            restored = previous
            if previous is not None:
                store.set_channel(channel, previous)
        unrestored: List[str] = []
        for name in names:
            # A replica may have DIED between its gate and this
            # rollback (the crash-racing-deploy case): restoring the
            # survivors must not be aborted by the corpse — the
            # supervisor resurrects it onto the restored channel head,
            # so skipping it here still converges the fleet.
            try:
                router.quiesce(name)
                try:
                    router._states[name].handle.poke()
                    if restored is not None:
                        _await_version(router, name, restored,
                                       rcfg.deploy_swap_timeout_s,
                                       sleep, clock)
                finally:
                    router.readmit(name)
            except Exception as e:
                unrestored.append(name)
                event("deploy_rollback_skip",
                      f"replica {name} unreachable during rollback "
                      f"({e}) — supervisor/resurrection owns it")
        report.update(status="rolled_back", reason=reason,
                      restored=restored, unrestored=unrestored)
        event("deploy_done",
              f"rolled back to {restored or '<unset>'}: {reason}")
        return report

    for name in names:
        step = {"replica": name, "outcome": "ok", "detail": ""}
        report["steps"].append(step)
        router.quiesce(name)
        event("deploy_drain", f"replica {name}: quiesced, draining")
        try:
            if not router.await_idle(name, rcfg.deploy_drain_timeout_s):
                step.update(outcome="drain_timeout",
                            detail="never reached idle")
                router.readmit(name)  # still on the old, good version
                return rollback(f"replica {name} drain timed out")

            router._states[name].handle.poke()
            event("deploy_swap",
                  f"replica {name}: poked watcher, awaiting "
                  f"{target_version}")
            if not _await_version(router, name, target_version,
                                  rcfg.deploy_swap_timeout_s,
                                  sleep, clock):
                snap = _health(router, name)
                step.update(
                    outcome="swap_failed",
                    detail=f"breaker={snap.get('breaker')} "
                           f"version={snap.get('model_version')}")
                router.readmit(name)
                return rollback(
                    f"replica {name} failed to swap to "
                    f"{target_version} (breaker "
                    f"{snap.get('breaker', '?')})")
            swapped.append(name)
        except Exception as e:
            # The replica DIED under us mid-step (poke/healthz raised):
            # that is a per-replica gate failure, not a deploy crash —
            # the whole-fleet rollback below is the contract.
            step.update(outcome="died", detail=repr(e))
            return rollback(f"replica {name} died mid-deploy: {e}")
        finally:
            if step["outcome"] == "ok":
                router.readmit(name)

        # Probation: the canary takes live traffic; any SLO burn or
        # breaker excursion aborts the roll and reverts the fleet.
        event("deploy_gate",
              f"replica {name}: probation {rcfg.deploy_probation_s}s "
              f"(burn gate < {rcfg.deploy_burn_max})")
        deadline = clock() + rcfg.deploy_probation_s
        while clock() < deadline:
            snap = _health(router, name)
            burn = float(snap.get("slo_fast_burn") or 0.0)
            breaker = snap.get("breaker", "closed")
            if (not snap or breaker != "closed"
                    or snap.get("slo_breached")
                    or burn >= rcfg.deploy_burn_max):
                step.update(
                    outcome="gate_failed",
                    detail=f"burn={burn} breaker={breaker} "
                           f"breached={snap.get('slo_breached')}")
                return rollback(
                    f"replica {name} failed probation "
                    f"(fast_burn={burn}, breaker={breaker})")
            sleep(min(0.05, rcfg.deploy_probation_s / 4))
        step["detail"] = f"serving {target_version}"

    event("deploy_done",
          f"channel {channel} now {target_version} on "
          f"{len(swapped)}/{len(names)} replica(s)")
    return report
