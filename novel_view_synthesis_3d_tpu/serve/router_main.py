"""Router process entrypoint:

    python -m novel_view_synthesis_3d_tpu.serve.router_main spec.json

The fleet router as its OWN OS process — the piece the chaos lane
SIGKILLs. The ingress reuses the replica wire protocol (serve/replica.py
ReplicaServer over a RouterCore adapter), so clients talk to the router
with the same `HttpReplica` handle + `submit_with_retry` they would use
against a single replica: a router crash surfaces as ReplicaUnreachable
(retryable by construction) and the client rides through the restart.

Crash-safety comes from the router journal (serve/journal.py): affinity
overrides and the outstanding-steps ledger are appended per dispatch, so
a respawned router replays them, re-derives every ring-home pin from the
consistent hash (zero recovered state), and reconciles the replayed
ledger against live /healthz. `/healthz` on the router reports the full
fleet snapshot INCLUDING the `recovery` provenance block — `nvs3d route
status` against a restarted router shows exactly what was reconstructed
from where.

Spec keys:
    name            router identity (default "router")
    results_folder  router telemetry dir (required)
    ready_file      readiness JSON path (required; heartbeat-touched)
    port            bind port (default 0 = ephemeral)
    replicas        [{"name", "url", "run_dir"}] fleet membership
                    (required)
    journal         journal path (default
                    <results_folder>/router_journal.jsonl)
    rcfg            {field: value} RouterConfig overrides
    heartbeat_s     ready-file touch period (default 2.0)

SIGTERM/SIGINT closes the router cleanly (poller joined, journal
flushed+closed); SIGKILL is what the journal exists for.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import sys
import threading
import time
from typing import Optional


class _CallTicket:
    """Minimal ticket over a blocking router call, matching the handle
    protocol ReplicaServer expects (the router's request() already
    blocks internally; the thread keeps the HTTP handler's timeout
    semantics identical to a replica's)."""

    def __init__(self, fn):
        self.request_id = -1
        self.model_version = ""
        self._result = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()

        def run():
            try:
                self._result = fn()
            except BaseException as e:
                self._error = e
            self._done.set()

        threading.Thread(target=run, daemon=True).start()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("router call still in flight")
        if self._error is not None:
            raise self._error
        return self._result


class RouterCore:
    """Adapter: FleetRouter behind the replica handle protocol, so
    ReplicaServer can serve it and HttpReplica can speak to it."""

    def __init__(self, name: str, router):
        self.name = str(name)
        self.router = router

    def healthz(self) -> dict:
        snap = self.router.fleet_snapshot()
        snap["status"] = "ok" if snap.get("healthy") else "degraded"
        snap["role"] = "router"
        snap["model_version"] = ""
        return snap

    def submit(self, cond, *, session=None, timeout_s=None, **kw):
        del session  # singles are stateless; affinity is orbits-only
        return _CallTicket(lambda: self.router.request(cond, **kw))

    def submit_trajectory(self, cond, poses, *, session=None,
                          timeout_s=None, **kw):
        return _CallTicket(lambda: self.router.request_trajectory(
            cond, poses, session=session, **kw))

    def begin_drain(self) -> None:
        pass  # retirement is the launcher's SIGTERM → close()

    def drain(self, timeout_s=None) -> None:
        pass

    def poke(self) -> None:
        self.router.poll_health()

    def metrics_text(self) -> str:
        from novel_view_synthesis_3d_tpu import obs

        return (obs.get_registry().render_prometheus()
                + self.router.fleet_metrics_text())

    def close(self) -> None:
        self.router.close()


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m novel_view_synthesis_3d_tpu.serve."
              "router_main <spec.json>", file=sys.stderr)
        return 2
    with open(argv[0]) as fh:
        spec = json.load(fh)

    from novel_view_synthesis_3d_tpu import obs
    from novel_view_synthesis_3d_tpu.config import RouterConfig, get_preset
    from novel_view_synthesis_3d_tpu.serve.replica import (
        HttpReplica,
        ReplicaServer,
    )
    from novel_view_synthesis_3d_tpu.serve.replica_main import _heartbeat
    from novel_view_synthesis_3d_tpu.serve.router import FleetRouter

    name = spec.get("name", "router")
    results_folder = spec["results_folder"]
    os.makedirs(results_folder, exist_ok=True)
    rcfg = dataclasses.replace(RouterConfig(),
                               **dict(spec.get("rcfg") or {}))
    replicas = [
        HttpReplica(r["name"], r["url"], run_dir=r.get("run_dir", ""))
        for r in spec["replicas"]]

    telemetry = obs.RunTelemetry.create(
        get_preset("tiny64").obs, results_folder, start_server=False)
    journal = spec.get("journal") or os.path.join(
        results_folder, "router_journal.jsonl")
    router = FleetRouter(
        replicas, rcfg=rcfg, tracer=telemetry.tracer,
        bus=telemetry.bus, start=True, journal=journal,
        run_dir=results_folder)
    core = RouterCore(name, router)
    server = ReplicaServer(core, port=int(spec.get("port", 0)))

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())

    ready = {"port": server.port, "pid": os.getpid(),
             "url": server.url(), "name": name,
             "recovery": router.recovery}
    tmp = spec["ready_file"] + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(ready, fh)
    os.replace(tmp, spec["ready_file"])
    threading.Thread(
        target=_heartbeat,
        args=(spec["ready_file"], stop,
              float(spec.get("heartbeat_s", 2.0))),
        daemon=True, name="ready-heartbeat").start()
    print(f"router {name} serving {len(replicas)} replica(s) on "
          f"{server.url()}"
          + (" (journal replayed)" if router.recovery else ""),
          flush=True)

    stop.wait()
    print(f"router {name}: closing", flush=True)
    # Give in-flight ingress threads a beat to settle before the poller
    # join — SIGTERM is the graceful path; abrupt death is the drill.
    time.sleep(0.1)
    try:
        router.close()
    finally:
        server.close()
        telemetry.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
