"""FleetRouter: least-step-debt dispatch, session affinity, failover.

The router is deliberately thin (the Pathways single-controller
argument, PAPERS.md): replicas own all model state; the router owns
three small tables —

  - a health cache: each replica's /healthz snapshot (step_debt,
    brownout_level, serve_state, breaker) polled every
    router.health_poll_s and aged out after router.health_ttl_s;
  - an outstanding-work ledger: denoise steps this router has in
    flight per replica, so dispatch pressure between polls is
    poll-fresh + local-accurate (two requests arriving between polls
    don't both see the same stale debt);
  - the affinity table: orbit session → replica. A trajectory's frame
    bank is device-resident on ONE replica, so every segment of a
    session must land there; the pin moves only when the pinned
    replica leaves the eligible set (drain, death, deploy quiesce),
    and the continuation is re-conditioned on the last delivered
    frame so the orbit stays seamless.

Failover is driven by PR 11's structured error contract: a replica
that died (ReplicaUnreachable), drained, or shed retryably triggers a
transparent re-route, bounded by router.retry_budget per request. When
EVERY eligible replica sheds in a full sweep, the fleet is saturated —
the router raises FleetSaturated (retryable, carrying the fleet's own
max retry_after_s) instead of burning the budget retry-storming, so
backpressure propagates to callers loudly and with server-paced
backoff (sample/client.submit_with_retry honors it).

Observability: the router threads one trace_id through every replica
hop (the replica's request_submit/request_respond rows carry it), and
writes its own rows through the obs bus/tracer — `router_submit` root,
one `router_hop` span per attempt (replica, attempt ordinal, outcome),
and a retrospective `router_respond` — so `nvs3d obs trace` can
reconstruct a cross-replica timeline from the fleet's merged
telemetry (obs/reqtrace.load_fleet_rows).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from novel_view_synthesis_3d_tpu import obs
from novel_view_synthesis_3d_tpu.config import RouterConfig
from novel_view_synthesis_3d_tpu.obs import reqtrace
from novel_view_synthesis_3d_tpu.sample.client import retry_delay_s
from novel_view_synthesis_3d_tpu.sample.service import (
    Rejected,
    ServeError,
    _normalize_poses,
)
from novel_view_synthesis_3d_tpu.serve.replica import ReplicaUnreachable

# Replica-side serve_state values the router will dispatch onto.
_DISPATCHABLE = ("ok",)


class NoReplicaAvailable(Rejected):
    """Every replica is dead, draining, or out of rotation. Retryable:
    a deploy readmits, a supervisor restarts — capacity returns."""

    def __init__(self, message: str, *, retry_after_s: float = 1.0):
        super().__init__(message, retryable=True,
                         retry_after_s=retry_after_s)


class FleetSaturated(Rejected):
    """Fleet-wide brownout: every eligible replica shed retryably in a
    full sweep. Carries the fleet's max retry_after_s so a herd of
    callers backs off on the servers' own estimate instead of
    retry-storming N replicas × retry_budget times each."""

    def __init__(self, message: str, *, retry_after_s: float):
        super().__init__(message, retryable=True,
                         retry_after_s=retry_after_s)


class _ReplicaState:
    __slots__ = ("handle", "health", "health_t", "outstanding",
                 "in_rotation", "reachable", "dispatches", "failures")

    def __init__(self, handle):
        self.handle = handle
        self.health: Optional[dict] = None
        self.health_t = float("-inf")
        self.outstanding = 0  # denoise steps in flight via THIS router
        self.in_rotation = True
        self.reachable = True
        self.dispatches = 0
        self.failures = 0


class FleetRouter:
    def __init__(self, replicas, *, rcfg: Optional[RouterConfig] = None,
                 tracer=None, bus=None, clock=time.monotonic,
                 sleep=time.sleep, start: bool = False,
                 metrics_server=None):
        """`replicas`: iterable of handles (serve/replica.py protocol).
        `tracer`/`bus` come from the router's own obs.RunTelemetry (or
        stay None for bare tests — every write is guarded). `start=True`
        launches the background health poller; tests poll manually.
        `metrics_server`: an obs.MetricsServer to hang the fleet
        aggregation on — the router's own /metrics then re-serves every
        replica's families relabeled with replica="<name>" (cleared on
        close)."""
        self.rcfg = rcfg or RouterConfig()
        self._states: "OrderedDict[str, _ReplicaState]" = OrderedDict()
        for h in replicas:
            if h.name in self._states:
                raise ValueError(f"duplicate replica name {h.name!r}")
            self._states[h.name] = _ReplicaState(h)
        self.tracer = tracer
        self.bus = bus
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._affinity: "OrderedDict[str, str]" = OrderedDict()
        self._next_rid = 0
        self._rr = 0  # tie-break rotation for equal-debt picks
        reg = obs.get_registry()
        self._m_requests = reg.counter(
            "nvs3d_router_requests_total",
            "requests routed, by final outcome")
        self._m_failovers = reg.counter(
            "nvs3d_router_failovers_total",
            "transparent re-routes, by reason")
        self._m_dispatch = reg.counter(
            "nvs3d_router_dispatch_total",
            "hops dispatched, by replica")
        self._m_healthy = reg.gauge(
            "nvs3d_router_replicas_healthy",
            "replicas reachable + dispatchable at last poll")
        self._m_debt = reg.gauge(
            "nvs3d_router_fleet_step_debt",
            "fleet step debt: polled replica debt + router outstanding")
        self._stop = threading.Event()
        self._poller: Optional[threading.Thread] = None
        self._metrics_server = metrics_server
        if metrics_server is not None:
            metrics_server.set_metrics_extra(self.fleet_metrics_text)
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self._poller is not None:
            return
        self._poller = threading.Thread(
            target=self._poll_loop, daemon=True, name="router-health")
        self._poller.start()

    def close(self) -> None:
        self._stop.set()
        if self._metrics_server is not None:
            self._metrics_server.set_metrics_extra(None)
            self._metrics_server = None
        if self._poller is not None:
            self._poller.join(timeout=10.0)
            self._poller = None

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            self.poll_health()
            self._stop.wait(self.rcfg.health_poll_s)

    # -- health --------------------------------------------------------
    def poll_health(self) -> Dict[str, Optional[dict]]:
        """Poll every replica's /healthz once; updates the cache, the
        fleet gauges, and emits replica_down/replica_up transitions."""
        now = self._clock()
        healthy = 0
        debt_total = 0
        for name, st in self._states.items():
            try:
                snap = st.handle.healthz()
                was_unreachable = not st.reachable
                st.health, st.health_t, st.reachable = snap, now, True
                if was_unreachable:
                    self._event("replica_up",
                                f"replica {name} reachable again")
            except Exception as e:
                if st.reachable:
                    self._event("replica_down",
                                f"replica {name} healthz failed: {e!r}")
                st.reachable = False
                st.health = None
                continue
            if self._dispatchable(st):
                healthy += 1
            debt_total += int(snap.get("step_debt", 0)) + st.outstanding
        self._m_healthy.set(float(healthy))
        self._m_debt.set(float(debt_total))
        return {name: st.health for name, st in self._states.items()}

    def _fresh(self, st: _ReplicaState) -> bool:
        return (st.health is not None
                and self._clock() - st.health_t <= self.rcfg.health_ttl_s)

    def _dispatchable(self, st: _ReplicaState) -> bool:
        if not (st.in_rotation and st.reachable):
            return False
        if not self._fresh(st):
            # Unknown health: stale snapshot. Dispatchable (the poller
            # may simply be off in a test), but _eligible ranks fresh
            # replicas first.
            return st.health is None or (
                st.health.get("serve_state",
                              st.health.get("status")) in _DISPATCHABLE)
        state = st.health.get("serve_state", st.health.get("status"))
        if state not in _DISPATCHABLE:
            return False
        return int(st.health.get("brownout_level", 0)) < 2

    def _debt(self, st: _ReplicaState) -> int:
        polled = int((st.health or {}).get("step_debt", 0))
        return polled + st.outstanding

    def _eligible(self, exclude=()) -> List[str]:
        return [name for name, st in self._states.items()
                if name not in exclude and self._dispatchable(st)]

    # -- dispatch policy ----------------------------------------------
    def pick(self, *, session: Optional[str] = None,
             exclude=()) -> str:
        """Least-step-debt replica; an orbit session's pin wins while
        the pinned replica stays eligible (the frame bank lives there).
        Raises NoReplicaAvailable when the eligible set is empty."""
        with self._lock:
            if session is not None:
                pinned = self._affinity.get(session)
                if pinned is not None and pinned not in exclude \
                        and self._dispatchable(self._states[pinned]):
                    self._affinity.move_to_end(session)
                    return pinned
            names = self._eligible(exclude)
            if not names:
                raise NoReplicaAvailable(
                    "no dispatchable replica (all dead, draining, "
                    "quiesced, or shedding)")
            self._rr += 1
            best = min(
                names,
                key=lambda n: (self._debt(self._states[n]),
                               (self._rr + hash(n)) % len(names)))
            if session is not None:
                self._pin(session, best)
            return best

    def _pin(self, session: str, name: str) -> None:
        # caller holds self._lock
        moved = self._affinity.get(session)
        self._affinity[session] = name
        self._affinity.move_to_end(session)
        while len(self._affinity) > self.rcfg.affinity_entries:
            self._affinity.popitem(last=False)
        if moved is not None and moved != name:
            self._event("router_affinity_move",
                        f"session {session}: {moved} -> {name}")

    # -- rotation control (deploys) -----------------------------------
    def quiesce(self, name: str) -> None:
        """Take a replica out of rotation (router-level drain begin):
        no new dispatches; orbit sessions re-pin on their next segment;
        in-flight work finishes on the replica."""
        self._states[name].in_rotation = False
        self._event("router_quiesce", f"replica {name} out of rotation")

    def readmit(self, name: str) -> None:
        self._states[name].in_rotation = True
        self._event("router_readmit", f"replica {name} back in rotation")

    def await_idle(self, name: str, timeout_s: float,
                   poll_s: float = 0.05) -> bool:
        """Router-level drain wait: poll the replica's healthz until
        queue_depth == 0 and step_debt == 0 (everything it owed is
        served). True on idle, False on timeout/unreachable."""
        deadline = self._clock() + timeout_s
        while self._clock() < deadline:
            try:
                snap = self._states[name].handle.healthz()
            except Exception:
                return False
            if (int(snap.get("queue_depth", 1)) == 0
                    and int(snap.get("step_debt", 1)) == 0):
                return True
            self._sleep(poll_s)
        return False

    def retire(self, name: str, timeout_s: Optional[float] = None) -> None:
        """Permanently remove a replica: quiesce, then run the PR 11
        drain state machine to completion (admissions reject retryably,
        queued + in-ring work finishes, worker exits)."""
        self.quiesce(name)
        st = self._states[name]
        try:
            st.handle.begin_drain()
            st.handle.drain(timeout_s)
        finally:
            st.reachable = False

    # -- request path --------------------------------------------------
    def request(self, cond, *, seed: int = 0, sample_steps=None,
                guidance_weight=None, deadline_ms=None,
                trace_id: Optional[str] = None, timeout_s: float = 600.0
                ) -> np.ndarray:
        """Route one single-shot request; blocks for the image.
        Transparent failover within router.retry_budget; fleet-wide
        shed raises FleetSaturated."""
        with self._lock:
            self._next_rid += 1
            rid = self._next_rid
        tid = reqtrace.mint(rid, trace_id)
        self._span("router_submit", 0.0, trace_id=tid,
                   span_id=reqtrace.root_span_id(tid), req_kind="single",
                   steps=int(sample_steps or 0))
        t0 = time.monotonic()
        steps_weight = int(sample_steps or 1)
        attempt = 0
        failovers = 0
        shed: Dict[str, float] = {}
        tried_dead: set = set()
        while True:
            try:
                # A replica that shed THIS request is excluded from its
                # retries: single-shots are stateless, so the budget is
                # spent exploring remaining capacity instead of
                # hammering the queue that just refused. (Trajectories
                # retry in place — the frame bank is worth waiting for.)
                name = self.pick(exclude=tried_dead | set(shed))
            except NoReplicaAvailable:
                if shed:
                    self._finish(tid, t0, "saturated", attempt, failovers)
                    raise FleetSaturated(
                        "fleet saturated: every eligible replica shed "
                        f"({sorted(shed)})",
                        retry_after_s=max(shed.values()) or 0.25
                    ) from None
                self._finish(tid, t0, "no_replica", attempt, failovers)
                raise
            st = self._states[name]
            attempt += 1
            t_hop = time.monotonic()
            st.outstanding += steps_weight
            try:
                ticket = st.handle.submit(
                    cond, seed=seed, sample_steps=sample_steps,
                    guidance_weight=guidance_weight,
                    deadline_ms=deadline_ms, trace_id=tid)
                img = ticket.result(timeout=timeout_s)
            except Exception as e:
                st.outstanding -= steps_weight
                retryable = bool(getattr(e, "retryable", False))
                self._hop(tid, name, attempt, t_hop,
                          "failover" if retryable else "failed", e)
                if isinstance(e, ReplicaUnreachable):
                    st.reachable = False
                    tried_dead.add(name)
                    self._event("replica_down",
                                f"replica {name} died mid-request: {e}")
                elif retryable:
                    shed[name] = max(
                        shed.get(name, 0.0),
                        float(getattr(e, "retry_after_s", 0.0) or 0.0))
                    if set(self._eligible()) <= set(shed):
                        # Full sweep shed: saturated, stop storming.
                        self._m_requests.inc(outcome="saturated")
                        self._finish(tid, t0, "saturated", attempt,
                                     failovers)
                        raise FleetSaturated(
                            "fleet saturated: every eligible replica "
                            f"shed ({sorted(shed)})",
                            retry_after_s=max(shed.values()) or 0.25
                        ) from e
                if not retryable or failovers >= self.rcfg.retry_budget:
                    self._m_requests.inc(outcome="failed")
                    self._finish(tid, t0, "failed", attempt, failovers)
                    raise
                failovers += 1
                self._m_failovers.inc(
                    reason="dead" if isinstance(e, ReplicaUnreachable)
                    else "shed")
                self._sleep(min(0.25, retry_delay_s(e, failovers - 1)))
                continue
            st.outstanding -= steps_weight
            st.dispatches += 1
            self._m_dispatch.inc(replica=name)
            self._hop(tid, name, attempt, t_hop, "ok", None)
            self._m_requests.inc(outcome="ok")
            self._finish(tid, t0, "ok", attempt, failovers)
            return img

    def request_trajectory(self, cond, poses, *, seed: int = 0,
                           sample_steps=None, guidance_weight=None,
                           deadline_ms=None, k_max=None,
                           session: Optional[str] = None,
                           trace_id: Optional[str] = None,
                           timeout_s: float = 600.0) -> np.ndarray:
        """Route one orbit; blocks for the stacked (N, H, W, 3) frames.

        The session (default: the trace id) pins the orbit to one
        replica — its frame bank lives there. A mid-orbit failure with
        partial frames (SampleAnomaly, replica death after streaming)
        fails over: the router re-pins, re-conditions on the LAST
        DELIVERED frame + its pose, and submits only the remaining
        poses, so the caller still receives a complete orbit."""
        poses_R, poses_t = _normalize_poses(poses)
        n_frames = int(poses_R.shape[0])
        with self._lock:
            self._next_rid += 1
            rid = self._next_rid
        tid = reqtrace.mint(rid, trace_id)
        session = session or tid
        self._span("router_submit", 0.0, trace_id=tid,
                   span_id=reqtrace.root_span_id(tid),
                   req_kind="trajectory", steps=int(sample_steps or 0),
                   frames=n_frames, session=session)
        t0 = time.monotonic()
        done: List[np.ndarray] = []
        attempt = 0
        failovers = 0
        shed: Dict[str, float] = {}
        tried_dead: set = set()
        base_cond = {k: np.asarray(v) for k, v in cond.items()}
        while len(done) < n_frames:
            try:
                name = self.pick(session=session, exclude=tried_dead)
            except NoReplicaAvailable:
                self._finish(tid, t0, "no_replica", attempt, failovers,
                             frames_done=len(done))
                if shed:
                    raise FleetSaturated(
                        "fleet saturated mid-orbit "
                        f"({len(done)}/{n_frames} frames)",
                        retry_after_s=max(shed.values()) or 0.25
                    ) from None
                raise
            st = self._states[name]
            attempt += 1
            start = len(done)
            if start == 0:
                hop_cond = base_cond
            else:
                # Continuation: condition on the last delivered frame
                # at its own pose — the bank on the NEW replica is
                # seeded exactly where the old one left off.
                hop_cond = {
                    "x": np.asarray(done[-1]),
                    "R1": poses_R[start - 1],
                    "t1": poses_t[start - 1],
                    "K": base_cond["K"],
                }
            hop_poses = {"R2": poses_R[start:], "t2": poses_t[start:]}
            weight = int(sample_steps or 1) * (n_frames - start)
            t_hop = time.monotonic()
            st.outstanding += weight
            try:
                ticket = st.handle.submit_trajectory(
                    hop_cond, hop_poses, seed=seed + attempt,
                    sample_steps=sample_steps,
                    guidance_weight=guidance_weight,
                    deadline_ms=deadline_ms, k_max=k_max, trace_id=tid)
                frames = ticket.result(timeout=timeout_s)
            except Exception as e:
                st.outstanding -= weight
                partial = getattr(e, "frames", None) or []
                done.extend(np.asarray(f) for f in partial)
                retryable = bool(getattr(e, "retryable", False))
                self._hop(tid, name, attempt, t_hop,
                          "failover" if retryable else "failed", e,
                          frames_done=len(done))
                if isinstance(e, ReplicaUnreachable):
                    st.reachable = False
                    tried_dead.add(name)
                    self._event("replica_down",
                                f"replica {name} died mid-orbit "
                                f"(session {session}, "
                                f"{len(done)}/{n_frames} frames): {e}")
                elif retryable:
                    shed[name] = max(
                        shed.get(name, 0.0),
                        float(getattr(e, "retry_after_s", 0.0) or 0.0))
                if not retryable or failovers >= self.rcfg.retry_budget:
                    self._m_requests.inc(outcome="failed")
                    self._finish(tid, t0, "failed", attempt, failovers,
                                 frames_done=len(done))
                    raise
                failovers += 1
                self._m_failovers.inc(
                    reason="dead" if isinstance(e, ReplicaUnreachable)
                    else "shed")
                with self._lock:
                    if self._affinity.get(session) == name:
                        del self._affinity[session]
                self._sleep(min(0.25, retry_delay_s(e, failovers - 1)))
                continue
            st.outstanding -= weight
            st.dispatches += 1
            self._m_dispatch.inc(replica=name)
            done.extend(np.asarray(f) for f in frames)
            self._hop(tid, name, attempt, t_hop, "ok", None,
                      frames_done=len(done))
        self._m_requests.inc(outcome="ok")
        self._finish(tid, t0, "ok", attempt, failovers,
                     frames_done=len(done))
        return np.stack(done)

    # -- fleet views ---------------------------------------------------
    def fleet_snapshot(self) -> dict:
        """Aggregated health for `nvs3d route status` and the bench
        artifacts: per-replica health + the fleet rollup."""
        replicas = {}
        healthy = 0
        debt = 0
        for name, st in self._states.items():
            replicas[name] = {
                "reachable": st.reachable,
                "in_rotation": st.in_rotation,
                "outstanding": st.outstanding,
                "dispatches": st.dispatches,
                "health": st.health,
            }
            if self._dispatchable(st):
                healthy += 1
            debt += self._debt(st)
        return {
            "replicas": replicas,
            "healthy": healthy,
            "total": len(self._states),
            "fleet_step_debt": debt,
        }

    def fleet_metrics_text(self) -> str:
        """Merged Prometheus exposition: every reachable replica's
        /metrics with a replica="<name>" label stamped onto each
        sample, HELP/TYPE headers deduped — one scrape surface for the
        whole fleet (obs.MetricsServer extra-text hook serves it)."""
        out: List[str] = []
        seen_meta = set()
        for name, st in self._states.items():
            try:
                text = st.handle.metrics_text()
            except Exception:
                continue
            for line in text.splitlines():
                if line.startswith("#"):
                    if line not in seen_meta:
                        seen_meta.add(line)
                        out.append(line)
                    continue
                if not line.strip():
                    continue
                out.append(_relabel(line, name))
        return "\n".join(out) + ("\n" if out else "")

    def fleet_slo(self) -> dict:
        """Fleet SLO rollup from the health cache: per-replica worst
        fast-burn + breach flags (the live view; offline attainment
        over merged telemetry is obs.slo.fleet_attainment)."""
        per = {}
        for name, st in self._states.items():
            h = st.health or {}
            per[name] = {
                "slo_fast_burn": h.get("slo_fast_burn"),
                "slo_breached": h.get("slo_breached"),
            }
        burns = [v["slo_fast_burn"] for v in per.values()
                 if isinstance(v["slo_fast_burn"], (int, float))]
        return {
            "replicas": per,
            "worst_fast_burn": max(burns) if burns else None,
            "any_breached": any(v["slo_breached"] for v in per.values()),
        }

    # -- telemetry plumbing -------------------------------------------
    def _span(self, name: str, dur_s: float, **attrs) -> None:
        if self.tracer is not None:
            self.tracer.add_span(name, dur_s, **attrs)

    def _event(self, kind: str, detail: str) -> None:
        if self.bus is not None:
            self.bus.event(0, kind, detail, echo="[router]")

    def _hop(self, tid: str, replica: str, attempt: int, t_hop: float,
             outcome: str, error, **extra) -> None:
        attrs = dict(trace_id=tid,
                     span_id=f"{tid}/h{attempt}",
                     parent_id=reqtrace.root_span_id(tid),
                     replica=replica, attempt=attempt, outcome=outcome)
        if error is not None:
            attrs["error"] = f"{type(error).__name__}: {error}"[:200]
        attrs.update(extra)
        self._span("router_hop", time.monotonic() - t_hop, **attrs)
        if outcome == "failover":
            self._event(
                "router_failover",
                f"trace {tid} attempt {attempt} on {replica}: "
                f"{type(error).__name__}: {error}")

    def _finish(self, tid: str, t0: float, outcome: str, attempts: int,
                failovers: int, **extra) -> None:
        self._span("router_respond", 0.0, trace_id=tid,
                   parent_id=reqtrace.root_span_id(tid),
                   outcome=outcome,
                   latency_s=round(time.monotonic() - t0, 6),
                   hops=attempts, failovers=failovers, **extra)
        if outcome == "saturated":
            self._event("router_shed",
                        f"trace {tid} shed after {attempts} attempt(s): "
                        "fleet-wide brownout")


def _relabel(sample_line: str, replica: str) -> str:
    """Stamp replica="<name>" onto one Prometheus sample line."""
    head, _, value = sample_line.rpartition(" ")
    if not head:
        return sample_line
    if head.endswith("}"):
        return f'{head[:-1]},replica="{replica}"}} {value}'
    return f'{head}{{replica="{replica}"}} {value}'
