"""FleetRouter: least-step-debt dispatch, consistent-hash affinity,
failover, and gray-failure defenses.

The router is deliberately thin (the Pathways single-controller
argument, PAPERS.md): replicas own all model state; the router owns
three small tables —

  - a health cache: each replica's /healthz snapshot (step_debt,
    brownout_level, serve_state, breaker, latency_p99_s) polled every
    router.health_poll_s and aged out after router.health_ttl_s;
  - an outstanding-work ledger: denoise steps this router has in
    flight per replica, so dispatch pressure between polls is
    poll-fresh + local-accurate (two requests arriving between polls
    don't both see the same stale debt);
  - the affinity layer: orbit session → replica. A trajectory's frame
    bank is device-resident on ONE replica, so every segment of a
    session must land there. The base mapping is a CONSISTENT-HASH
    RING (replica names → vnode positions, session id → first vnode
    clockwise): it is derived from nothing but the replica set, so a
    freshly restarted router computes bit-identical pins with ZERO
    recovered state. Only DEVIATIONS from the ring (a session that
    migrated off its ring home on failover — its bank now lives
    elsewhere) are stored, as bounded-LRU overrides, and journaled.

Crash-safe restart: pass `journal=` (a path or serve/journal.py
RouterJournal) and the router appends hop/orbit/pin records as it
dispatches; a restarting router replays the journal — affinity
overrides are restored, and the unresolved outstanding-steps ledger
seeds dispatch pressure until the first /healthz poll of each replica
supersedes it (the replica's own step_debt gauge is authoritative —
that is the reconciliation). `fleet_snapshot()["recovery"]` reports
the reconstruction provenance (`nvs3d route status` prints it).

Gray-failure defenses (a replica that is slow is worse than one that
is dead — the dead one fails fast):

  - demotion: with router.demote_p99_factor set, a replica whose
    polled latency_p99_s is >= factor × the fleet's best p99 is
    demoted — dispatched to only when no un-demoted replica is
    eligible (router_demote/router_promote events);
  - hedged dispatch: with router.hedge_delay_s set, a stateless
    single whose first replica has not answered after the delay is
    sent again to the next ring replica; first response wins, the
    loser is abandoned (`router_hedge` span, nvs3d_router_hedges_total
    by winner). Trajectories never hedge — the frame bank is
    single-homed;
  - per-hop timeout: router.hop_timeout_s bounds what ONE replica
    attempt may consume of the request's total timeout; a wedged
    replica costs one hop budget, not the whole client deadline
    (`router_hop_timeout` event, the hop fails over).

Failover is driven by PR 11's structured error contract: a replica
that died (ReplicaUnreachable), drained, or shed retryably triggers a
transparent re-route, bounded by router.retry_budget per request. When
EVERY eligible replica sheds in a full sweep, the fleet is saturated —
the router raises FleetSaturated (retryable, carrying the fleet's own
max retry_after_s) instead of burning the budget retry-storming, so
backpressure propagates to callers loudly and with server-paced
backoff (sample/client.submit_with_retry honors it).

Observability: the router threads one trace_id through every replica
hop (the replica's request_submit/request_respond rows carry it), and
writes its own rows through the obs bus/tracer — `router_submit` root,
one `router_hop` span per attempt (replica, attempt ordinal, outcome),
`router_hedge` for hedge races, and a retrospective `router_respond`
— so `nvs3d obs trace` can reconstruct a cross-replica timeline from
the fleet's merged telemetry (obs/reqtrace.load_fleet_rows).
"""

from __future__ import annotations

import bisect
import hashlib
import os
import sys
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Set

import numpy as np

from novel_view_synthesis_3d_tpu import obs
from novel_view_synthesis_3d_tpu.config import RouterConfig
from novel_view_synthesis_3d_tpu.obs import reqtrace
from novel_view_synthesis_3d_tpu.sample.client import retry_delay_s
from novel_view_synthesis_3d_tpu.sample.service import (
    DeadlineExceeded,
    Rejected,
    ServeError,
    _normalize_poses,
)
from novel_view_synthesis_3d_tpu.serve import journal as journal_mod
from novel_view_synthesis_3d_tpu.serve.journal import RouterJournal
from novel_view_synthesis_3d_tpu.serve.replica import ReplicaUnreachable

# Replica-side serve_state values the router will dispatch onto.
_DISPATCHABLE = ("ok",)


class NoReplicaAvailable(Rejected):
    """Every replica is dead, draining, or out of rotation. Retryable:
    a deploy readmits, a supervisor restarts — capacity returns."""

    def __init__(self, message: str, *, retry_after_s: float = 1.0):
        super().__init__(message, retryable=True,
                         retry_after_s=retry_after_s)


class FleetSaturated(Rejected):
    """Fleet-wide brownout: every eligible replica shed retryably in a
    full sweep. Carries the fleet's max retry_after_s so a herd of
    callers backs off on the servers' own estimate instead of
    retry-storming N replicas × retry_budget times each."""

    def __init__(self, message: str, *, retry_after_s: float):
        super().__init__(message, retryable=True,
                         retry_after_s=retry_after_s)


class HopTimeout(Rejected):
    """One replica attempt exceeded the per-hop timeout budget
    (router.hop_timeout_s): the replica is wedged-or-slow, not
    provably dead — the hop is abandoned and the request fails over.
    Retryable by construction (like ReplicaUnreachable, the router
    stops waiting; a stateless resubmit elsewhere cannot double-count
    a CLIENT-visible result)."""

    def __init__(self, message: str):
        super().__init__(message, retryable=True, retry_after_s=0.0)


def _hash64(key: str) -> int:
    """Stable 64-bit position — hashlib, NOT hash(): Python string
    hashing is salted per process, and the whole point of the ring is
    that two router incarnations derive identical pins."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(),
        "big")


class HashRing:
    """Consistent-hash ring over replica names.

    Each replica contributes `vnodes` points at blake2b("name#i");
    `lookup(key)` walks clockwise from blake2b(key) to the first
    point whose replica is not excluded. Deterministic in (replica
    set, vnodes, key) and nothing else — the crash-safe affinity
    contract. The exclude walk doubles as deterministic failover
    order: the "next ring replica" for hedging and pin migration."""

    def __init__(self, names, vnodes: int = 64):
        self.vnodes = max(1, int(vnodes))
        self._points = sorted(
            (_hash64(f"{name}#{i}"), str(name))
            for name in names for i in range(self.vnodes))
        self._keys = [p[0] for p in self._points]

    def lookup(self, key: str, exclude=frozenset()) -> Optional[str]:
        if not self._points:
            return None
        i = bisect.bisect_right(self._keys, _hash64(str(key)))
        seen: Set[str] = set()
        for j in range(len(self._points)):
            name = self._points[(i + j) % len(self._points)][1]
            if name in seen:
                continue
            seen.add(name)
            if name not in exclude:
                return name
        return None

    def digest(self) -> str:
        """Digest of the full vnode table: two routers derive identical
        pins for EVERY key iff their digests match — the serve_bench
        router-restart drill asserts this bit-reproduction across
        incarnations instead of sampling keys."""
        h = hashlib.blake2b(digest_size=8)
        for pos, name in self._points:
            h.update(pos.to_bytes(8, "big"))
            h.update(name.encode("utf-8"))
        return h.hexdigest()


class _ReplicaState:
    __slots__ = ("handle", "health", "health_t", "outstanding",
                 "in_rotation", "reachable", "dispatches", "failures",
                 "demoted", "recovered")

    def __init__(self, handle):
        self.handle = handle
        self.health: Optional[dict] = None
        self.health_t = float("-inf")
        self.outstanding = 0  # denoise steps in flight via THIS router
        self.in_rotation = True
        self.reachable = True
        self.dispatches = 0
        self.failures = 0
        self.demoted = False   # gray-failure: slow-but-alive
        self.recovered = 0     # journal-replayed steps, pre-first-poll


class FleetRouter:
    def __init__(self, replicas, *, rcfg: Optional[RouterConfig] = None,
                 tracer=None, bus=None, clock=time.monotonic,
                 sleep=time.sleep, start: bool = False,
                 metrics_server=None, journal=None, run_dir: str = ""):
        """`replicas`: iterable of handles (serve/replica.py protocol).
        `tracer`/`bus` come from the router's own obs.RunTelemetry (or
        stay None for bare tests — every write is guarded). `start=True`
        launches the background health poller; tests poll manually.
        `metrics_server`: an obs.MetricsServer to hang the fleet
        aggregation on. `journal`: a path or RouterJournal — enables
        the crash-safe append-only journal; an existing file is
        REPLAYED first (affinity overrides restored, unresolved ledger
        seeded until reconciled against live /healthz). `run_dir`: the
        router's own folder (stall diagnoses, default journal home)."""
        self.rcfg = rcfg or RouterConfig()
        self.run_dir = str(run_dir or "")
        self._states: "OrderedDict[str, _ReplicaState]" = OrderedDict()
        for h in replicas:
            if h.name in self._states:
                raise ValueError(f"duplicate replica name {h.name!r}")
            self._states[h.name] = _ReplicaState(h)
        self._ring = HashRing(self._states.keys(),
                              vnodes=self.rcfg.affinity_vnodes)
        self.tracer = tracer
        self.bus = bus
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        # Affinity OVERRIDES only (sessions living off their ring
        # home); ring-derived pins need no state at all.
        self._pins: "OrderedDict[str, str]" = OrderedDict()
        # Last replica each live session dispatched to (status view +
        # affinity-move detection); bounded like the override table.
        self._sessions: "OrderedDict[str, str]" = OrderedDict()
        self._next_rid = 0
        self._rr = 0  # tie-break rotation for equal-debt picks
        reg = obs.get_registry()
        self._m_requests = reg.counter(
            "nvs3d_router_requests_total",
            "requests routed, by final outcome")
        self._m_failovers = reg.counter(
            "nvs3d_router_failovers_total",
            "transparent re-routes, by reason")
        self._m_dispatch = reg.counter(
            "nvs3d_router_dispatch_total",
            "hops dispatched, by replica")
        self._m_hedges = reg.counter(
            "nvs3d_router_hedges_total",
            "hedged single dispatches, by winner (primary|hedge)")
        self._m_healthy = reg.gauge(
            "nvs3d_router_replicas_healthy",
            "replicas reachable + dispatchable at last poll")
        self._m_demoted = reg.gauge(
            "nvs3d_router_replicas_demoted",
            "replicas demoted for gray failure (slow p99) at last poll")
        self._m_debt = reg.gauge(
            "nvs3d_router_fleet_step_debt",
            "fleet step debt: polled replica debt + router outstanding")
        self._stop = threading.Event()
        self._poller: Optional[threading.Thread] = None
        self._metrics_server = metrics_server
        self.journal: Optional[RouterJournal] = None
        self.recovery: Optional[dict] = None
        if journal is not None:
            self._init_journal(journal)
        if metrics_server is not None:
            metrics_server.set_metrics_extra(self.fleet_metrics_text)
        if start:
            self.start()

    # -- journal replay / recovery ------------------------------------
    def _init_journal(self, journal) -> None:
        if isinstance(journal, RouterJournal):
            jr = journal
        else:
            path = str(journal)
            if os.path.isdir(path) or path.endswith(os.sep):
                path = os.path.join(path, "router_journal.jsonl")
            jr = RouterJournal(
                path, snapshot_every=self.rcfg.journal_snapshot_every)
        replayed = journal_mod.replay(jr.path)
        self.journal = jr
        if not replayed or not replayed["records"]:
            return
        pins_restored = 0
        for session, name in replayed["pins"].items():
            if name in self._states:
                self._pins[session] = name
                self._sessions[session] = name
                pins_restored += 1
        recovered = {}
        for name, steps in replayed["outstanding"].items():
            if name in self._states and steps > 0:
                self._states[name].recovered = int(steps)
                recovered[name] = int(steps)
        self.recovery = {
            "journal": replayed["path"],
            "records": replayed["records"],
            "torn": replayed["torn"],
            "pins_restored": pins_restored,
            "orbits_seen": len(replayed["orbits"]),
            "recovered_steps": recovered,
            "reconciled": {},
        }
        self._event(
            "router_journal_replay",
            f"replayed {replayed['records']} record(s) from "
            f"{jr.path}: {sum(recovered.values())} unresolved step(s) "
            f"across {len(recovered)} replica(s), {pins_restored} "
            f"affinity override(s) restored"
            + (f", {replayed['torn']} torn line(s) skipped"
               if replayed["torn"] else ""))

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self._poller is not None:
            return
        self._poller = threading.Thread(
            target=self._poll_loop, daemon=True, name="router-health")
        self._poller.start()

    def close(self, timeout: float = 10.0) -> None:
        """Stop the poller and release the metrics hook. A poller that
        does not join within `timeout` is WEDGED (a healthz call stuck
        past every socket timeout) — the router writes a PR 2-style
        all-thread-stack diagnosis (stall_router_close_<n>.txt under
        run_dir) and raises instead of silently leaking the thread."""
        self._stop.set()
        if self._metrics_server is not None:
            self._metrics_server.set_metrics_extra(None)
            self._metrics_server = None
        poller = self._poller
        if poller is not None:
            poller.join(timeout=timeout)
            if poller.is_alive():
                self._dump_close_stall(poller, timeout)
                raise RuntimeError(
                    f"router health poller still alive after "
                    f"{timeout:.1f}s join (close()): thread-stack "
                    f"diagnosis written under {self.run_dir or '<unset>'!r} "
                    "(stall_router_close_*.txt)")
            self._poller = None
        if self.journal is not None:
            self.journal.close()

    def _dump_close_stall(self, thread: threading.Thread,
                          timeout: float) -> None:
        """Wedged-poller diagnosis: every thread's stack to a stall_*
        file (stderr when even that fails — the diagnosis must never
        be the second fault), plus a `stall` event row."""
        from novel_view_synthesis_3d_tpu.utils import watchdog

        self._event(
            "stall",
            f"close(): health poller {thread.name!r} wedged past the "
            f"{timeout:.1f}s join; diagnosis stall_router_close_*.txt")
        body = (f"fleet-router close(): poller {thread.name!r} still "
                f"alive after join timeout {timeout:.1f}s\n"
                f"time: {time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}"
                "\n\n" + watchdog.thread_stacks())
        try:
            if not self.run_dir:
                raise OSError("router has no run_dir")
            os.makedirs(self.run_dir, exist_ok=True)
            n = 0
            while os.path.exists(os.path.join(
                    self.run_dir, f"stall_router_close_{n}.txt")):
                n += 1
            path = os.path.join(self.run_dir,
                                f"stall_router_close_{n}.txt")
            with open(path, "w") as fh:
                fh.write(body)
            print(f"[router] wedged-poller diagnosis: {path}",
                  file=sys.stderr, flush=True)
        except OSError:
            print(body, file=sys.stderr, flush=True)

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            self.poll_health()
            self._stop.wait(self.rcfg.health_poll_s)

    # -- health --------------------------------------------------------
    def poll_health(self) -> Dict[str, Optional[dict]]:
        """Poll every replica's /healthz once; updates the cache, the
        fleet gauges, gray-failure demotion, and emits
        replica_down/replica_up transitions. A replica's first
        successful poll supersedes (reconciles) any journal-recovered
        outstanding steps — the replica's own step_debt gauge already
        counts whatever survived the old router."""
        now = self._clock()
        healthy = 0
        debt_total = 0
        for name, st in self._states.items():
            try:
                snap = st.handle.healthz()
                was_unreachable = not st.reachable
                st.health, st.health_t, st.reachable = snap, now, True
                if st.recovered:
                    if self.recovery is not None:
                        self.recovery["reconciled"][name] = st.recovered
                    self._event(
                        "router_journal_reconcile",
                        f"replica {name}: {st.recovered} journal-"
                        f"recovered step(s) superseded by live "
                        f"step_debt={int(snap.get('step_debt', 0))}")
                    st.recovered = 0
                if was_unreachable:
                    self._event("replica_up",
                                f"replica {name} reachable again")
            except Exception as e:
                if st.reachable:
                    self._event("replica_down",
                                f"replica {name} healthz failed: {e!r}")
                st.reachable = False
                st.health = None
                continue
            if self._dispatchable(st):
                healthy += 1
            debt_total += int(snap.get("step_debt", 0)) + st.outstanding
        self._update_demotion()
        self._m_healthy.set(float(healthy))
        self._m_debt.set(float(debt_total))
        return {name: st.health for name, st in self._states.items()}

    def _update_demotion(self) -> None:
        """Gray-failure scoring: a replica whose fresh latency_p99_s is
        >= demote_p99_factor × the fleet's BEST fresh p99 is demoted.
        Needs >= 2 reporting replicas — with one report there is no
        peer to be slow relative to; when everyone slows together
        (shared cause) nobody is demoted."""
        factor = float(self.rcfg.demote_p99_factor or 0.0)
        p99s: Dict[str, float] = {}
        if factor > 0.0:
            for name, st in self._states.items():
                if st.reachable and self._fresh(st):
                    p = float((st.health or {}).get("latency_p99_s")
                              or 0.0)
                    if p > 0.0:
                        p99s[name] = p
        best = min(p99s.values()) if len(p99s) >= 2 else 0.0
        demoted = 0
        for name, st in self._states.items():
            was = st.demoted
            st.demoted = bool(best > 0.0
                              and p99s.get(name, 0.0) >= factor * best)
            if st.demoted:
                demoted += 1
            if st.demoted and not was:
                self._event(
                    "router_demote",
                    f"replica {name} demoted: p99 "
                    f"{p99s.get(name, 0.0) * 1000:.0f}ms >= "
                    f"{factor:g}x fleet best {best * 1000:.0f}ms")
            elif was and not st.demoted:
                self._event("router_promote",
                            f"replica {name} promoted: p99 back within "
                            f"{factor:g}x fleet best")
        self._m_demoted.set(float(demoted))

    def _fresh(self, st: _ReplicaState) -> bool:
        return (st.health is not None
                and self._clock() - st.health_t <= self.rcfg.health_ttl_s)

    def _dispatchable(self, st: _ReplicaState) -> bool:
        if not (st.in_rotation and st.reachable):
            return False
        if not self._fresh(st):
            # Unknown health: stale snapshot. Dispatchable (the poller
            # may simply be off in a test), but _eligible ranks fresh
            # replicas first.
            return st.health is None or (
                st.health.get("serve_state",
                              st.health.get("status")) in _DISPATCHABLE)
        state = st.health.get("serve_state", st.health.get("status"))
        if state not in _DISPATCHABLE:
            return False
        return int(st.health.get("brownout_level", 0)) < 2

    def _debt(self, st: _ReplicaState) -> int:
        polled = int((st.health or {}).get("step_debt", 0))
        return polled + st.outstanding + st.recovered

    def _eligible(self, exclude=()) -> List[str]:
        return [name for name, st in self._states.items()
                if name not in exclude and self._dispatchable(st)]

    def _outstanding_map(self) -> Dict[str, int]:
        return {name: st.outstanding + st.recovered
                for name, st in self._states.items()
                if st.outstanding or st.recovered}

    # -- dispatch policy ----------------------------------------------
    def ring_pin(self, session: str) -> Optional[str]:
        """The session's zero-state ring home — what a freshly
        restarted router with no journal would derive. Public so the
        bench/tests can assert bit-reproduction."""
        return self._ring.lookup(session)

    def pick(self, *, session: Optional[str] = None,
             exclude=()) -> str:
        """Dispatch choice. Singles: least step debt among un-demoted
        eligible replicas (demoted ones only when nothing better).
        Sessions: the affinity override if one exists and is usable,
        else the consistent-hash ring walk (home first, then ring
        order) — deviations from the ring home are stored as overrides
        so the orbit's frame bank stays single-homed. Raises
        NoReplicaAvailable when the eligible set is empty."""
        with self._lock:
            if session is not None:
                name = self._pick_session_locked(session, set(exclude))
                if name is None:
                    raise NoReplicaAvailable(
                        "no dispatchable replica (all dead, draining, "
                        "quiesced, or shedding)")
                return name
            names = self._eligible(exclude)
            if not names:
                raise NoReplicaAvailable(
                    "no dispatchable replica (all dead, draining, "
                    "quiesced, or shedding)")
            pref = [n for n in names if not self._states[n].demoted]
            pool = pref or names
            self._rr += 1
            return min(
                pool,
                key=lambda n: (self._debt(self._states[n]),
                               (self._rr + hash(n)) % len(pool)))

    def _pick_session_locked(self, session: str,
                             exclude: Set[str]) -> Optional[str]:
        # caller holds self._lock
        cur = self._pins.get(session)
        if cur is not None:
            if (cur not in exclude and cur in self._states
                    and self._dispatchable(self._states[cur])):
                self._pins.move_to_end(session)
                self._note_session(session, cur)
                return cur
            # The override's replica left the eligible set: drop the
            # override and fall back to the ring walk.
            del self._pins[session]
            if self.journal is not None:
                self.journal.unpin(session)
        elig = set(self._eligible(exclude))
        if not elig:
            return None
        pref = ({n for n in elig if not self._states[n].demoted}
                or elig)
        choice = self._ring.lookup(
            session, exclude=set(self._states) - pref)
        if choice is None:
            choice = self._ring.lookup(
                session, exclude=set(self._states) - elig)
        if choice is None:
            return None
        home = self._ring.lookup(session)
        if choice != home:
            # Deviation from the ring: must be remembered (the bank
            # lives on `choice` now; a restart must not send the next
            # segment back to a resurrected home).
            self._set_pin_locked(session, choice, home)
        self._note_session(session, choice)
        return choice

    def _set_pin_locked(self, session: str, name: str,
                        home: Optional[str]) -> None:
        # caller holds self._lock
        self._pins[session] = name
        self._pins.move_to_end(session)
        if self.journal is not None:
            self.journal.pin(session, name, home or "")
        while len(self._pins) > self.rcfg.affinity_entries:
            old, _ = self._pins.popitem(last=False)
            if self.journal is not None:
                self.journal.unpin(old)

    def _note_session(self, session: str, name: str) -> None:
        # caller holds self._lock
        prev = self._sessions.get(session)
        self._sessions[session] = name
        self._sessions.move_to_end(session)
        while len(self._sessions) > self.rcfg.affinity_entries:
            self._sessions.popitem(last=False)
        if prev is not None and prev != name:
            self._event("router_affinity_move",
                        f"session {session}: {prev} -> {name}")

    def _unpin_locked(self, session: str, name: str) -> None:
        # caller holds self._lock; drop pin only if it still points at
        # the failed replica (a concurrent segment may have re-pinned)
        if self._pins.get(session) == name:
            del self._pins[session]
            if self.journal is not None:
                self.journal.unpin(session)

    def _hedge_peer(self, key: str, exclude: Set[str]) -> Optional[str]:
        """The hedge target: next ring replica (deterministic) among
        eligible, un-demoted (falling back to demoted) peers."""
        with self._lock:
            elig = set(self._eligible(exclude))
            if not elig:
                return None
            pref = ({n for n in elig if not self._states[n].demoted}
                    or elig)
            return self._ring.lookup(
                key, exclude=set(self._states) - pref)

    # -- rotation control (deploys) -----------------------------------
    def quiesce(self, name: str) -> None:
        """Take a replica out of rotation (router-level drain begin):
        no new dispatches; orbit sessions re-pin on their next segment;
        in-flight work finishes on the replica."""
        self._states[name].in_rotation = False
        self._event("router_quiesce", f"replica {name} out of rotation")

    def readmit(self, name: str) -> None:
        self._states[name].in_rotation = True
        self._event("router_readmit", f"replica {name} back in rotation")

    def await_idle(self, name: str, timeout_s: float,
                   poll_s: float = 0.05) -> bool:
        """Router-level drain wait: poll the replica's healthz until
        queue_depth == 0 and step_debt == 0 (everything it owed is
        served). True on idle, False on timeout/unreachable."""
        deadline = self._clock() + timeout_s
        while self._clock() < deadline:
            try:
                snap = self._states[name].handle.healthz()
            except Exception:
                return False
            if (int(snap.get("queue_depth", 1)) == 0
                    and int(snap.get("step_debt", 1)) == 0):
                return True
            self._sleep(poll_s)
        return False

    def retire(self, name: str, timeout_s: Optional[float] = None) -> None:
        """Permanently remove a replica: quiesce, then run the PR 11
        drain state machine to completion (admissions reject retryably,
        queued + in-ring work finishes, worker exits)."""
        self.quiesce(name)
        st = self._states[name]
        try:
            st.handle.begin_drain()
            st.handle.drain(timeout_s)
        finally:
            st.reachable = False

    # -- the hop engine -----------------------------------------------
    def _run_hop(self, *, tid: str, name: str, att: dict, weight: int,
                 deadline: float, submit, tried_dead: Set[str],
                 shed: Dict[str, float], hedge: bool = False,
                 err_extra=None, ok_extra=None):
        """Dispatch one hop-group (primary + at most one hedge) and
        wait for the first response, enforcing the per-hop timeout
        budget. Owns ALL per-hop bookkeeping — ledger, journal, hop
        spans, replica_down events, tried_dead/shed classification —
        for primary and hedge alike. Returns (result, winner_name);
        raises the terminal error for the outer failover loop to
        budget (tried_dead/shed already updated)."""
        rcfg = self.rcfg
        hop_cap = rcfg.hop_timeout_s if rcfg.hop_timeout_s > 0 \
            else float("inf")
        hedge_at = (time.monotonic() + rcfg.hedge_delay_s
                    if hedge and rcfg.hedge_delay_s > 0
                    else float("inf"))
        entries: List[dict] = []
        last_err: Optional[BaseException] = None

        def extras(e=None) -> dict:
            if err_extra is None:
                return {}
            return err_extra(e) if callable(err_extra) else dict(err_extra)

        def j_done(nm: str, outcome: str) -> None:
            if self.journal is not None:
                self.journal.hop_done(tid, nm, weight, outcome)

        def launch(nm: str) -> Optional[dict]:
            nonlocal last_err
            st = self._states[nm]
            st.outstanding += weight
            if self.journal is not None:
                self.journal.hop(tid, nm, weight)
                self.journal.maybe_snapshot(self._outstanding_map())
            att["n"] += 1
            ent = {"name": nm, "st": st, "attempt": att["n"],
                   "t0": time.monotonic()}
            try:
                ent["ticket"] = submit(nm)
            except Exception as e:
                settle_error(ent, e)
                return None
            return ent

        def settle_error(ent: dict, e: BaseException) -> None:
            nonlocal last_err
            nm = ent["name"]
            ent["st"].outstanding -= weight
            retryable = bool(getattr(e, "retryable", False))
            outcome = "failover" if retryable else "failed"
            self._hop(tid, nm, ent["attempt"], ent["t0"], outcome, e,
                      **extras(e))
            j_done(nm, outcome)
            if isinstance(e, ReplicaUnreachable):
                ent["st"].reachable = False
                tried_dead.add(nm)
                self._event("replica_down",
                            f"replica {nm} died mid-request: {e}")
            elif retryable:
                shed[nm] = max(
                    shed.get(nm, 0.0),
                    float(getattr(e, "retry_after_s", 0.0) or 0.0))
            last_err = e

        def settle_timeout(ent: dict, budget_s: float) -> None:
            nonlocal last_err
            nm = ent["name"]
            ent["st"].outstanding -= weight
            self._hop(tid, nm, ent["attempt"], ent["t0"], "hop_timeout",
                      None, **extras(None))
            j_done(nm, "hop_timeout")
            tried_dead.add(nm)
            self._event(
                "router_hop_timeout",
                f"trace {tid} attempt {ent['attempt']} on {nm}: no "
                f"response within the {budget_s:.1f}s per-hop budget; "
                "abandoning hop (replica keeps computing)")
            last_err = HopTimeout(
                f"replica {nm} exceeded the {budget_s:.1f}s per-hop "
                "timeout budget")

        def abandon(ent: dict, outcome: str) -> None:
            ent["st"].outstanding -= weight
            self._hop(tid, ent["name"], ent["attempt"], ent["t0"],
                      outcome, None)
            j_done(ent["name"], outcome)

        primary = launch(name)
        if primary is None:
            raise last_err
        entries.append(primary)
        hedge_launched = False
        poll = 0.02
        while entries:
            now = time.monotonic()
            if now >= deadline:
                for ent in list(entries):
                    settle_timeout(ent, deadline - ent["t0"])
                raise DeadlineExceeded(
                    f"request {tid}: total router timeout exhausted "
                    "waiting on the fleet")
            if (not hedge_launched and now >= hedge_at
                    and any(e is primary for e in entries)):
                hedge_launched = True
                peer = self._hedge_peer(
                    tid, exclude=({e["name"] for e in entries}
                                  | tried_dead | set(shed)))
                if peer is not None:
                    ent = launch(peer)
                    if ent is not None:
                        entries.append(ent)
                        self._event(
                            "router_hedge",
                            f"trace {tid}: hedging {name} -> {peer} "
                            f"after {rcfg.hedge_delay_s * 1000:.0f}ms "
                            "without a response")
            for ent in list(entries):
                now = time.monotonic()
                hop_deadline = min(ent["t0"] + hop_cap, deadline)
                if now >= hop_deadline:
                    entries.remove(ent)
                    settle_timeout(ent, min(hop_cap,
                                            deadline - ent["t0"]))
                    continue
                slice_t = min(poll, hop_deadline - now)
                if not hedge_launched and hedge_at > now:
                    slice_t = min(slice_t, hedge_at - now)
                try:
                    result = ent["ticket"].result(
                        timeout=max(0.0, slice_t))
                except TimeoutError:
                    continue  # not done yet; budgets checked above
                except Exception as e:
                    entries.remove(ent)
                    settle_error(ent, e)
                    if not getattr(e, "retryable", False):
                        # Deterministic failure — it would fail
                        # identically on the hedge; stop the race.
                        for other in list(entries):
                            entries.remove(other)
                            abandon(other, "cancelled")
                        raise
                    continue
                # -- winner ------------------------------------------
                entries.remove(ent)
                for other in list(entries):
                    entries.remove(other)
                    abandon(other, "hedge_abandoned")
                ent["st"].outstanding -= weight
                ent["st"].dispatches += 1
                self._m_dispatch.inc(replica=ent["name"])
                ok_attrs = {}
                if ok_extra is not None:
                    ok_attrs = ok_extra(result)
                if hedge_launched:
                    ok_attrs["hedged"] = True
                self._hop(tid, ent["name"], ent["attempt"], ent["t0"],
                          "ok", None, **ok_attrs)
                j_done(ent["name"], "ok")
                if hedge_launched:
                    winner = ("primary" if ent is primary else "hedge")
                    self._m_hedges.inc(winner=winner)
                    self._span(
                        "router_hedge",
                        time.monotonic() - primary["t0"],
                        trace_id=tid,
                        span_id=f"{tid}/g{primary['attempt']}",
                        parent_id=reqtrace.root_span_id(tid),
                        primary=name, winner=ent["name"],
                        delay_s=rcfg.hedge_delay_s, outcome=winner)
                return result, ent["name"]
        raise last_err

    # -- request path --------------------------------------------------
    def request(self, cond, *, seed: int = 0, sample_steps=None,
                guidance_weight=None, deadline_ms=None,
                trace_id: Optional[str] = None, timeout_s: float = 600.0
                ) -> np.ndarray:
        """Route one single-shot request; blocks for the image.
        Transparent failover within router.retry_budget; fleet-wide
        shed raises FleetSaturated; per-hop timeouts and hedged
        dispatch apply when configured (singles are stateless, so a
        duplicate in flight is waste, never corruption)."""
        with self._lock:
            self._next_rid += 1
            rid = self._next_rid
        tid = reqtrace.mint(rid, trace_id)
        self._span("router_submit", 0.0, trace_id=tid,
                   span_id=reqtrace.root_span_id(tid), req_kind="single",
                   steps=int(sample_steps or 0))
        t0 = time.monotonic()
        deadline = t0 + float(timeout_s)
        steps_weight = int(sample_steps or 1)
        att = {"n": 0}
        failovers = 0
        shed: Dict[str, float] = {}
        tried_dead: Set[str] = set()

        def submit(nm: str):
            return self._states[nm].handle.submit(
                cond, seed=seed, sample_steps=sample_steps,
                guidance_weight=guidance_weight,
                deadline_ms=deadline_ms, trace_id=tid)

        while True:
            try:
                # A replica that shed THIS request is excluded from its
                # retries: single-shots are stateless, so the budget is
                # spent exploring remaining capacity instead of
                # hammering the queue that just refused. (Trajectories
                # retry in place — the frame bank is worth waiting for.)
                name = self.pick(exclude=tried_dead | set(shed))
            except NoReplicaAvailable:
                if shed:
                    self._finish(tid, t0, "saturated", att["n"],
                                 failovers)
                    raise FleetSaturated(
                        "fleet saturated: every eligible replica shed "
                        f"({sorted(shed)})",
                        retry_after_s=max(shed.values()) or 0.25
                    ) from None
                self._finish(tid, t0, "no_replica", att["n"], failovers)
                raise
            try:
                img, _winner = self._run_hop(
                    tid=tid, name=name, att=att, weight=steps_weight,
                    deadline=deadline, submit=submit,
                    tried_dead=tried_dead, shed=shed, hedge=True)
            except Exception as e:
                retryable = bool(getattr(e, "retryable", False))
                if (retryable and shed
                        and not isinstance(e, (ReplicaUnreachable,
                                               HopTimeout))
                        and set(self._eligible()) <= set(shed)):
                    # Full sweep shed: saturated, stop storming.
                    self._m_requests.inc(outcome="saturated")
                    self._finish(tid, t0, "saturated", att["n"],
                                 failovers)
                    raise FleetSaturated(
                        "fleet saturated: every eligible replica "
                        f"shed ({sorted(shed)})",
                        retry_after_s=max(shed.values()) or 0.25
                    ) from e
                if not retryable or failovers >= self.rcfg.retry_budget:
                    self._m_requests.inc(outcome="failed")
                    self._finish(tid, t0, "failed", att["n"], failovers)
                    raise
                failovers += 1
                self._m_failovers.inc(
                    reason="dead" if isinstance(e, ReplicaUnreachable)
                    else "wedged" if isinstance(e, HopTimeout)
                    else "shed")
                self._sleep(min(0.25, retry_delay_s(e, failovers - 1)))
                continue
            self._m_requests.inc(outcome="ok")
            self._finish(tid, t0, "ok", att["n"], failovers)
            return img

    def request_trajectory(self, cond, poses, *, seed: int = 0,
                           sample_steps=None, guidance_weight=None,
                           deadline_ms=None, k_max=None,
                           session: Optional[str] = None,
                           trace_id: Optional[str] = None,
                           timeout_s: float = 600.0) -> np.ndarray:
        """Route one orbit; blocks for the stacked (N, H, W, 3) frames.

        The session (default: the trace id) pins the orbit to one
        replica — its frame bank lives there, at the session's
        consistent-hash ring home unless a failover moved it (the
        deviation is stored + journaled). A mid-orbit failure with
        partial frames (SampleAnomaly, replica death after streaming)
        fails over: the router re-pins along the ring, re-conditions
        on the LAST DELIVERED frame + its pose, and submits only the
        remaining poses, so the caller still receives a complete
        orbit. Trajectories never hedge; the per-hop timeout budget
        still applies (a wedged bank-holder is abandoned and the orbit
        stitched onto a survivor)."""
        poses_R, poses_t = _normalize_poses(poses)
        n_frames = int(poses_R.shape[0])
        with self._lock:
            self._next_rid += 1
            rid = self._next_rid
        tid = reqtrace.mint(rid, trace_id)
        session = session or tid
        self._span("router_submit", 0.0, trace_id=tid,
                   span_id=reqtrace.root_span_id(tid),
                   req_kind="trajectory", steps=int(sample_steps or 0),
                   frames=n_frames, session=session)
        if self.journal is not None:
            self.journal.orbit(tid, session, n_frames,
                               int(sample_steps or 1))
        t0 = time.monotonic()
        deadline = t0 + float(timeout_s)
        done: List[np.ndarray] = []
        att = {"n": 0}
        failovers = 0
        shed: Dict[str, float] = {}
        tried_dead: Set[str] = set()
        base_cond = {k: np.asarray(v) for k, v in cond.items()}
        while len(done) < n_frames:
            try:
                name = self.pick(session=session, exclude=tried_dead)
            except NoReplicaAvailable:
                self._finish(tid, t0, "no_replica", att["n"], failovers,
                             frames_done=len(done))
                if shed:
                    raise FleetSaturated(
                        "fleet saturated mid-orbit "
                        f"({len(done)}/{n_frames} frames)",
                        retry_after_s=max(shed.values()) or 0.25
                    ) from None
                raise
            start = len(done)
            if start == 0:
                hop_cond = base_cond
            else:
                # Continuation: condition on the last delivered frame
                # at its own pose — the bank on the NEW replica is
                # seeded exactly where the old one left off.
                hop_cond = {
                    "x": np.asarray(done[-1]),
                    "R1": poses_R[start - 1],
                    "t1": poses_t[start - 1],
                    "K": base_cond["K"],
                }
            hop_poses = {"R2": poses_R[start:], "t2": poses_t[start:]}
            weight = int(sample_steps or 1) * (n_frames - start)
            attempt_seed = seed + att["n"] + 1

            def submit(nm: str, _c=hop_cond, _p=hop_poses,
                       _s=attempt_seed):
                return self._states[nm].handle.submit_trajectory(
                    _c, _p, seed=_s, sample_steps=sample_steps,
                    guidance_weight=guidance_weight,
                    deadline_ms=deadline_ms, k_max=k_max, trace_id=tid)

            try:
                frames, _winner = self._run_hop(
                    tid=tid, name=name, att=att, weight=weight,
                    deadline=deadline, submit=submit,
                    tried_dead=tried_dead, shed=shed, hedge=False,
                    err_extra=lambda e: {"frames_done": len(done) + len(
                        getattr(e, "frames", None) or [])},
                    ok_extra=lambda fr: {"frames_done":
                                         len(done) + len(fr)})
            except Exception as e:
                partial = getattr(e, "frames", None) or []
                done.extend(np.asarray(f) for f in partial)
                retryable = bool(getattr(e, "retryable", False))
                if isinstance(e, (ReplicaUnreachable, HopTimeout)):
                    if isinstance(e, ReplicaUnreachable):
                        self._event(
                            "replica_down",
                            f"replica {name} died mid-orbit "
                            f"(session {session}, "
                            f"{len(done)}/{n_frames} frames): {e}")
                    with self._lock:
                        self._unpin_locked(session, name)
                if not retryable or failovers >= self.rcfg.retry_budget:
                    self._m_requests.inc(outcome="failed")
                    self._finish(tid, t0, "failed", att["n"], failovers,
                                 frames_done=len(done))
                    raise
                failovers += 1
                self._m_failovers.inc(
                    reason="dead" if isinstance(e, ReplicaUnreachable)
                    else "wedged" if isinstance(e, HopTimeout)
                    else "shed")
                self._sleep(min(0.25, retry_delay_s(e, failovers - 1)))
                continue
            done.extend(np.asarray(f) for f in frames)
        self._m_requests.inc(outcome="ok")
        self._finish(tid, t0, "ok", att["n"], failovers,
                     frames_done=len(done))
        return np.stack(done)

    # -- fleet views ---------------------------------------------------
    def fleet_snapshot(self) -> dict:
        """Aggregated health for `nvs3d route status` and the bench
        artifacts: per-replica health + the fleet rollup, affinity
        provenance, and (after a journaled restart) the journal
        reconstruction record."""
        replicas = {}
        healthy = 0
        demoted = 0
        debt = 0
        for name, st in self._states.items():
            replicas[name] = {
                "reachable": st.reachable,
                "in_rotation": st.in_rotation,
                "outstanding": st.outstanding,
                "dispatches": st.dispatches,
                "demoted": st.demoted,
                "recovered": st.recovered,
                "health": st.health,
            }
            if self._dispatchable(st):
                healthy += 1
            if st.demoted:
                demoted += 1
            debt += self._debt(st)
        with self._lock:
            affinity = {
                "vnodes": self._ring.vnodes,
                "ring_digest": self._ring.digest(),
                "overrides": dict(self._pins),
                "sessions": dict(self._sessions),
            }
        return {
            "replicas": replicas,
            "healthy": healthy,
            "demoted": demoted,
            "total": len(self._states),
            "fleet_step_debt": debt,
            "affinity": affinity,
            "recovery": self.recovery,
        }

    def fleet_metrics_text(self) -> str:
        """Merged Prometheus exposition: every reachable replica's
        /metrics with a replica="<name>" label stamped onto each
        sample, HELP/TYPE headers deduped — one scrape surface for the
        whole fleet (obs.MetricsServer extra-text hook serves it)."""
        out: List[str] = []
        seen_meta = set()
        for name, st in self._states.items():
            try:
                text = st.handle.metrics_text()
            except Exception:
                continue
            for line in text.splitlines():
                if line.startswith("#"):
                    if line not in seen_meta:
                        seen_meta.add(line)
                        out.append(line)
                    continue
                if not line.strip():
                    continue
                out.append(_relabel(line, name))
        return "\n".join(out) + ("\n" if out else "")

    def fleet_slo(self) -> dict:
        """Fleet SLO rollup from the health cache: per-replica worst
        fast-burn + breach flags (the live view; offline attainment
        over merged telemetry is obs.slo.fleet_attainment)."""
        per = {}
        for name, st in self._states.items():
            h = st.health or {}
            per[name] = {
                "slo_fast_burn": h.get("slo_fast_burn"),
                "slo_breached": h.get("slo_breached"),
                "latency_p99_s": h.get("latency_p99_s"),
            }
        burns = [v["slo_fast_burn"] for v in per.values()
                 if isinstance(v["slo_fast_burn"], (int, float))]
        return {
            "replicas": per,
            "worst_fast_burn": max(burns) if burns else None,
            "any_breached": any(v["slo_breached"] for v in per.values()),
        }

    # -- telemetry plumbing -------------------------------------------
    def _span(self, name: str, dur_s: float, **attrs) -> None:
        if self.tracer is not None:
            self.tracer.add_span(name, dur_s, **attrs)

    def _event(self, kind: str, detail: str) -> None:
        if self.bus is not None:
            self.bus.event(0, kind, detail, echo="[router]")

    def _hop(self, tid: str, replica: str, attempt: int, t_hop: float,
             outcome: str, error, **extra) -> None:
        attrs = dict(trace_id=tid,
                     span_id=f"{tid}/h{attempt}",
                     parent_id=reqtrace.root_span_id(tid),
                     replica=replica, attempt=attempt, outcome=outcome)
        if error is not None:
            attrs["error"] = f"{type(error).__name__}: {error}"[:200]
        attrs.update(extra)
        self._span("router_hop", time.monotonic() - t_hop, **attrs)
        if outcome == "failover":
            self._event(
                "router_failover",
                f"trace {tid} attempt {attempt} on {replica}: "
                f"{type(error).__name__}: {error}")

    def _finish(self, tid: str, t0: float, outcome: str, attempts: int,
                failovers: int, **extra) -> None:
        self._span("router_respond", 0.0, trace_id=tid,
                   parent_id=reqtrace.root_span_id(tid),
                   outcome=outcome,
                   latency_s=round(time.monotonic() - t0, 6),
                   hops=attempts, failovers=failovers, **extra)
        if outcome == "saturated":
            self._event("router_shed",
                        f"trace {tid} shed after {attempts} attempt(s): "
                        "fleet-wide brownout")


def _relabel(sample_line: str, replica: str) -> str:
    """Stamp replica="<name>" onto one Prometheus sample line."""
    head, _, value = sample_line.rpartition(" ")
    if not head:
        return sample_line
    if head.endswith("}"):
        return f'{head[:-1]},replica="{replica}"}} {value}'
    return f'{head}{{replica="{replica}"}} {value}'
