"""Append-only router journal: crash-safe dispatch state.

The consistent-hash ring makes affinity pins derivable with zero
recovered state, but two pieces of router state are NOT derivable:

  - the outstanding-steps ledger (dispatch pressure the router added
    between health polls — a restarted router that forgets it starts
    blind and double-loads the busiest replica until the first poll);
  - affinity OVERRIDES (an orbit that migrated off its ring home on
    failover now has its frame bank on the override replica — the ring
    alone would send its next segment back to the resurrected home).

Both are tiny and append-friendly, so the journal is a JSONL file:
one object per line, flushed per record, torn tails tolerated on
replay (a SIGKILL mid-write must not poison the restart). Record
kinds:

    hop       {t, tid, replica, w}          steps dispatched
    hop_done  {t, tid, replica, w, outcome} steps resolved
    orbit     {t, tid, session, frames, steps}  admitted orbit
    pin       {t, session, replica, home}   affinity override created
    unpin     {t, session}                  override dropped
    snap      {t, outstanding: {replica: steps}}  ledger checkpoint

Replay folds records newest-snapshot-forward into {outstanding, pins,
orbits} plus provenance counters. The RESTARTED router treats replayed
outstanding as a pre-poll prior only: the first successful /healthz
poll of a replica supersedes it (the replica's own step_debt gauge is
authoritative — work the dead router had in flight either finished or
is counted there), which is the reconcile-against-live-healthz step.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional


class RouterJournal:
    """Append-only JSONL writer + replayer for FleetRouter state.

    Thread-safe; every append is flushed (the contract is crash-safe
    REPLAY, not zero-loss — a torn final line loses one hop record,
    which reconciliation against /healthz absorbs)."""

    def __init__(self, path: str, *, snapshot_every: int = 32,
                 clock=time.time):
        self.path = str(path)
        self.snapshot_every = max(1, int(snapshot_every))
        self._clock = clock
        self._lock = threading.Lock()
        self._since_snap = 0
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fh = open(self.path, "a", encoding="utf-8")

    # -- writer surface ------------------------------------------------
    def _append(self, rec: dict) -> None:
        rec["t"] = round(self._clock(), 3)
        line = json.dumps(rec, sort_keys=True)
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(line + "\n")
            self._fh.flush()

    def hop(self, tid: str, replica: str, weight: int) -> None:
        self._append({"k": "hop", "tid": tid, "replica": replica,
                      "w": int(weight)})
        self._since_snap += 1

    def hop_done(self, tid: str, replica: str, weight: int,
                 outcome: str) -> None:
        self._append({"k": "hop_done", "tid": tid, "replica": replica,
                      "w": int(weight), "outcome": outcome})

    def orbit(self, tid: str, session: str, frames: int,
              steps: int) -> None:
        self._append({"k": "orbit", "tid": tid, "session": session,
                      "frames": int(frames), "steps": int(steps)})

    def pin(self, session: str, replica: str, home: str) -> None:
        self._append({"k": "pin", "session": session,
                      "replica": replica, "home": home})

    def unpin(self, session: str) -> None:
        self._append({"k": "unpin", "session": session})

    def maybe_snapshot(self, outstanding: Dict[str, int]) -> None:
        """Checkpoint the ledger every `snapshot_every` hop records so
        replay folds from the newest snapshot, not file start."""
        if self._since_snap < self.snapshot_every:
            return
        self._since_snap = 0
        self._append({"k": "snap",
                      "outstanding": {k: int(v)
                                      for k, v in outstanding.items()
                                      if v}})

    def close(self) -> None:
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None:
            fh.close()


def replay(path: str) -> Optional[dict]:
    """Fold a journal back into router state. None when the file does
    not exist (fresh start — no provenance to report).

    Returns {"outstanding": {replica: steps}, "pins": {session:
    replica}, "orbits": {session: record}, "records": n, "torn": n,
    "path": path} — `outstanding` is the unresolved-hop ledger from the
    newest snapshot forward; `pins` the surviving affinity overrides.
    """
    if not os.path.exists(path):
        return None
    records = []
    torn = 0
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                torn += 1  # SIGKILL mid-write: skip, keep folding
    # Fold from the newest ledger snapshot forward; pins/orbits fold
    # over the WHOLE file (they are idempotent last-writer-wins).
    last_snap = None
    for i, rec in enumerate(records):
        if rec.get("k") == "snap":
            last_snap = i
    outstanding: Dict[str, int] = {}
    start = 0
    if last_snap is not None:
        outstanding.update({str(k): int(v) for k, v in
                            (records[last_snap].get("outstanding")
                             or {}).items()})
        start = last_snap + 1
    for rec in records[start:]:
        kind = rec.get("k")
        if kind == "hop":
            outstanding[rec["replica"]] = (
                outstanding.get(rec["replica"], 0) + int(rec["w"]))
        elif kind == "hop_done":
            outstanding[rec["replica"]] = (
                outstanding.get(rec["replica"], 0) - int(rec["w"]))
    outstanding = {k: v for k, v in outstanding.items() if v > 0}
    pins: Dict[str, str] = {}
    orbits: Dict[str, dict] = {}
    for rec in records:
        kind = rec.get("k")
        if kind == "pin":
            pins[rec["session"]] = rec["replica"]
        elif kind == "unpin":
            pins.pop(rec["session"], None)
        elif kind == "orbit":
            orbits[rec["session"]] = rec
    return {"outstanding": outstanding, "pins": pins, "orbits": orbits,
            "records": len(records), "torn": torn, "path": path}
