"""Self-healing fleet: supervised replica resurrection.

`FleetSupervisor` owns the replica PROCESSES the way train/supervisor.py
owns the training process (the PR 2 discipline): detect death, restart
into the SAME spec with bounded exponential backoff, give up loudly when
the budget is spent. Three detectors, cheapest first:

  1. process exit — ``proc.poll()`` is not None (SIGKILL, OOM, crash);
  2. stale heartbeat — the replica's ready-file mtime (touched every
     ``heartbeat_s`` by serve/replica_main.py) is older than
     ``supervisor_heartbeat_max_age_s``: the process is alive but its
     event loop is wedged. A stat, no HTTP round-trip to a hung server;
  3. consecutive /healthz failures — ``supervisor_health_fails`` probe
     errors in a row (half-dead network path, wedged HTTP thread pool).

Resurrection respawns ``python -m …serve.replica_main <spec.json>`` with
the same spec file, which pins the SAME port (``adopt`` rewrites the
spec with the concrete port from the first ready file) — so the
replica's URL never changes and the router readmits it through its
natural health poll, no router-side registration dance. Before the
``replica_resurrect`` event fires, the supervisor verifies the new
process is READY (ready-file pid matches the spawn) and HEALTHY
(/healthz status ok) and serving the EXPECTED model version (the
registry channel head when the spec names a registry, else the version
the dead incarnation last reported): a resurrected replica that came
back wrong is killed and the attempt counts against the budget.

Backoff: ``min(cap, backoff_s * 2**(restarts-1))`` per slot. Budget
exhaustion (``supervisor_max_restarts``) marks the slot FAILED loudly
(``replica_giveup`` event + stderr) and stops touching it — a
crash-looping spec needs a human, not a hotter loop.

Everything external is injectable (spawn, probe, heartbeat age, clock,
sleep) so tier-1 tests drill every detector with fakes; the defaults
drive real subprocesses for serve_bench --fleet's chaos phases.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from novel_view_synthesis_3d_tpu import obs
from novel_view_synthesis_3d_tpu.config import RouterConfig


@dataclasses.dataclass
class ReplicaSpec:
    """One supervised slot: where the replica's spec/ready files live
    and the URL the fleet knows it by (stable across respawns)."""

    name: str
    spec_path: str        # replica_main spec JSON (respawned verbatim)
    ready_file: str
    url: str = ""         # filled from the ready file on adopt
    log_path: str = ""    # respawned stdout/stderr sink ("" = inherit)


class _Slot:
    __slots__ = ("spec", "proc", "restarts", "health_fails", "failed",
                 "last_version", "resurrections")

    def __init__(self, spec: ReplicaSpec):
        self.spec = spec
        self.proc = None
        self.restarts = 0
        self.health_fails = 0
        self.failed = False
        self.last_version = ""
        self.resurrections = 0


def _default_spawn(spec: ReplicaSpec):
    cmd = [sys.executable, "-m",
           "novel_view_synthesis_3d_tpu.serve.replica_main",
           spec.spec_path]
    if spec.log_path:
        with open(spec.log_path, "ab") as log:
            return subprocess.Popen(cmd, stdout=log,
                                    stderr=subprocess.STDOUT)
    return subprocess.Popen(cmd)


def _default_probe(spec: ReplicaSpec) -> dict:
    from novel_view_synthesis_3d_tpu.serve.replica import HttpReplica

    return HttpReplica(spec.name, spec.url, health_timeout_s=3.0,
                       connect_timeout_s=3.0).healthz()


class FleetSupervisor:
    """Watches replica processes; resurrects the dead, demotes nothing
    (slow-but-alive is the ROUTER's problem — gray-failure demotion and
    hedging live there; the supervisor only acts on dead/wedged)."""

    def __init__(self, specs: List[ReplicaSpec], *,
                 rcfg: Optional[RouterConfig] = None,
                 bus=None, registry=None,
                 spawn: Optional[Callable[[ReplicaSpec], object]] = None,
                 probe: Optional[Callable[[ReplicaSpec], dict]] = None,
                 heartbeat_age: Optional[
                     Callable[[ReplicaSpec], Optional[float]]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.rcfg = rcfg or RouterConfig()
        self.bus = bus
        self._spawn = spawn or _default_spawn
        self._probe = probe or _default_probe
        self._heartbeat_age = heartbeat_age or self._ready_file_age
        self._clock = clock
        self._sleep = sleep
        self._slots: Dict[str, _Slot] = {
            s.name: _Slot(s) for s in specs}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = registry if registry is not None else obs.get_registry()
        self._m_restarts = reg.counter(
            "nvs3d_replica_restarts_total",
            "replica processes resurrected by the fleet supervisor")

    # -- wiring --------------------------------------------------------
    def adopt(self, name: str, proc) -> None:
        """Register an already-running replica process (the launcher
        spawned the first generation; the supervisor owns respawns).
        Reads the ready file to learn the URL and PINS the concrete
        port into the spec file so every respawn binds the same
        address — the router's replica handles stay valid."""
        slot = self._slots[name]
        slot.proc = proc
        try:
            with open(slot.spec.ready_file) as fh:
                ready = json.load(fh)
        except (OSError, ValueError):
            return
        if ready.get("url"):
            slot.spec.url = ready["url"]
        port = int(ready.get("port") or 0)
        if port:
            try:
                with open(slot.spec.spec_path) as fh:
                    spec_json = json.load(fh)
                if int(spec_json.get("port", 0)) != port:
                    spec_json["port"] = port
                    tmp = slot.spec.spec_path + ".tmp"
                    with open(tmp, "w") as fh:
                        json.dump(spec_json, fh, indent=1)
                    os.replace(tmp, slot.spec.spec_path)
            except (OSError, ValueError):
                pass  # unpinned port: respawn still works, URL may move

    # -- detection -----------------------------------------------------
    @staticmethod
    def _ready_file_age(spec: ReplicaSpec) -> Optional[float]:
        try:
            return max(0.0, time.time()
                       - os.path.getmtime(spec.ready_file))
        except OSError:
            return None  # not ready yet / mid-replace: no signal

    def check(self) -> List[str]:
        """One scan over all slots; resurrects anything dead/wedged.
        Returns the names acted on (for tests and the bench)."""
        acted = []
        for name, slot in sorted(self._slots.items()):
            if slot.failed or slot.proc is None:
                continue
            reason = self._diagnose(slot)
            if reason is None:
                continue
            acted.append(name)
            self._resurrect(slot, reason)
        return acted

    def _diagnose(self, slot: _Slot) -> Optional[str]:
        rc = slot.proc.poll()
        if rc is not None:
            return f"process exited rc={rc}"
        age = self._heartbeat_age(slot.spec)
        max_age = float(self.rcfg.supervisor_heartbeat_max_age_s)
        if age is not None and max_age > 0 and age > max_age:
            return f"heartbeat stale ({age:.1f}s > {max_age:.1f}s)"
        try:
            snap = self._probe(slot.spec)
        except Exception as e:
            slot.health_fails += 1
            if slot.health_fails >= int(self.rcfg.supervisor_health_fails):
                return (f"{slot.health_fails} consecutive health "
                        f"probe failures (last: {e})")
            return None
        slot.health_fails = 0
        if snap.get("model_version"):
            slot.last_version = str(snap["model_version"])
        return None

    # -- resurrection --------------------------------------------------
    def _expected_version(self, slot: _Slot) -> str:
        """The model version the resurrected replica must report: the
        registry channel head when the spec subscribes to one (the new
        process boots from it), else whatever the dead incarnation last
        reported ("" = no constraint — synthetic weights)."""
        try:
            with open(slot.spec.spec_path) as fh:
                spec_json = json.load(fh)
            reg = spec_json.get("registry") or {}
            if reg.get("dir"):
                from novel_view_synthesis_3d_tpu.registry import (
                    RegistryStore)

                head = RegistryStore(reg["dir"]).read_channel(
                    reg.get("channel", "stable"))
                if head:
                    return head
        except Exception:
            pass
        return slot.last_version

    def _resurrect(self, slot: _Slot, reason: str) -> bool:
        name = slot.spec.name
        slot.restarts += 1
        slot.health_fails = 0
        if slot.restarts > int(self.rcfg.supervisor_max_restarts):
            slot.failed = True
            detail = (f"replica {name} dead ({reason}) and restart "
                      f"budget spent ({self.rcfg.supervisor_max_restarts})"
                      " — slot FAILED, human needed")
            self._event("replica_giveup", detail)
            print(f"[fleet-supervisor] GIVING UP: {detail}",
                  file=sys.stderr, flush=True)
            return False
        self._event("replica_dead", f"replica {name}: {reason} "
                                    f"(restart {slot.restarts}/"
                                    f"{self.rcfg.supervisor_max_restarts})")
        self._kill_quietly(slot.proc)
        delay = min(float(self.rcfg.supervisor_backoff_cap_s),
                    float(self.rcfg.supervisor_backoff_s)
                    * (2.0 ** (slot.restarts - 1)))
        if delay > 0:
            self._sleep(delay)
        expected = self._expected_version(slot)
        try:
            os.remove(slot.spec.ready_file)
        except OSError:
            pass  # stale ready file would fake readiness via old pid
        slot.proc = self._spawn(slot.spec)
        if not self._await_ready(slot):
            # Spawn died or never became ready: leave the corpse for
            # the next scan, which re-detects and burns another retry.
            self._event("replica_resurrect_failed",
                        f"replica {name}: respawn not ready within "
                        f"{self.rcfg.supervisor_ready_timeout_s:.0f}s")
            return False
        try:
            snap = self._probe(slot.spec)
        except Exception as e:
            self._event("replica_resurrect_failed",
                        f"replica {name}: respawn unprobeable ({e})")
            return False
        got = str(snap.get("model_version", ""))
        if snap.get("status") != "ok" or (expected and got != expected):
            # Came back wrong — kill it; the exit is re-detected and
            # the attempt has already burned a unit of budget.
            self._event("replica_resurrect_failed",
                        f"replica {name}: respawn unhealthy "
                        f"(status={snap.get('status')!r}, "
                        f"version={got!r}, want {expected!r})")
            self._kill_quietly(slot.proc)
            return False
        slot.resurrections += 1
        slot.last_version = got or expected
        self._m_restarts.inc(replica=name)
        self._event(
            "replica_resurrect",
            f"replica {name} resurrected ({reason}; backoff {delay:.1f}s,"
            f" restart {slot.restarts}/{self.rcfg.supervisor_max_restarts},"
            f" pid {getattr(slot.proc, 'pid', '?')},"
            f" version {got or '<synthetic>'})")
        return True

    def _await_ready(self, slot: _Slot) -> bool:
        deadline = self._clock() + float(
            self.rcfg.supervisor_ready_timeout_s)
        pid = getattr(slot.proc, "pid", None)
        while self._clock() < deadline:
            if slot.proc.poll() is not None:
                return False
            try:
                with open(slot.spec.ready_file) as fh:
                    ready = json.load(fh)
            except (OSError, ValueError):
                ready = None
            if ready is not None and (pid is None
                                      or ready.get("pid") == pid):
                if ready.get("url"):
                    slot.spec.url = ready["url"]
                return True
            self._sleep(0.05)
        return False

    @staticmethod
    def _kill_quietly(proc) -> None:
        try:
            if proc is not None and proc.poll() is None:
                proc.kill()
            if proc is not None:
                proc.wait(timeout=10.0)
        except Exception:
            pass

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "FleetSupervisor":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-supervisor")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(float(self.rcfg.supervisor_poll_s)):
            try:
                self.check()
            except Exception as e:  # pragma: no cover - defensive
                print(f"[fleet-supervisor] scan error: {e!r}",
                      file=sys.stderr, flush=True)

    def close(self, timeout: float = 10.0) -> None:
        """Stop the scan thread. Does NOT kill the replicas — process
        retirement is the launcher's call (SIGTERM → drain)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    # -- introspection -------------------------------------------------
    def status(self) -> Dict[str, dict]:
        out = {}
        for name, slot in sorted(self._slots.items()):
            out[name] = {
                "pid": getattr(slot.proc, "pid", None),
                "alive": (slot.proc is not None
                          and slot.proc.poll() is None),
                "restarts": slot.restarts,
                "resurrections": slot.resurrections,
                "health_fails": slot.health_fails,
                "failed": slot.failed,
                "model_version": slot.last_version,
            }
        return out

    def procs(self) -> Dict[str, object]:
        """Current process handle per slot (respawns replace the
        launcher's originals — teardown must SIGTERM THESE)."""
        return {name: slot.proc for name, slot in self._slots.items()
                if slot.proc is not None}

    def _event(self, kind: str, detail: str) -> None:
        if self.bus is not None:
            self.bus.event(0, kind, detail, echo="[fleet]")
