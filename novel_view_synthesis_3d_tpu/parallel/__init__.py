from novel_view_synthesis_3d_tpu.parallel.dist import (  # noqa: F401
    initialize_distributed,
    local_batch_size,
)
from novel_view_synthesis_3d_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    make_mesh,
    replicate,
    replicated,
    shard_batch,
)
