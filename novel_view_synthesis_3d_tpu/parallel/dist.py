"""Multi-host bring-up (SURVEY.md §2.3: the reference is single-host only —
`jax.device_count()` over local GPUs, no process coordination).

On TPU pods each host runs the same program; `jax.distributed.initialize`
wires the processes together (DCN for control, ICI for collectives). On
single-host (or under tests) this is a no-op.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Optional, Tuple

import jax

# Structured exit code for "the accelerator backend never answered" —
# historically the code bench.py exits with (BENCH_r0* rc=3), now shared by
# every entry point that probes (cli train/sample/eval, bench, watchers).
# Distinct from utils/watchdog.EXIT_STALL (74): unreachable-at-startup and
# stalled-mid-run are different diagnoses.
EXIT_BACKEND_UNREACHABLE = 3

# Last require_backend failure reason (one line), for callers that emit a
# structured result object after catching the SystemExit — bench.py writes
# {"rc": 3, "reason": ...} so a BENCH_r0*.json records WHY a round produced
# no number instead of a bare "parsed": null.
LAST_FAILURE_REASON: Optional[str] = None


def _is_initialized() -> bool:
    """jax.distributed.is_initialized() with a fallback for jax builds that
    predate it (< 0.5): the distributed client handle in jax._src is the
    same thing the public accessor reads. Still backend-free either way."""
    if hasattr(jax.distributed, "is_initialized"):
        return bool(jax.distributed.is_initialized())
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Initialize multi-process JAX if we're in a multi-host environment.

    On Cloud TPU VMs `jax.distributed.initialize()` auto-discovers the pod
    topology from the metadata server; explicit args cover other clusters.
    Safe to call unconditionally: single-process environments skip init.

    NOTE: must not touch the XLA backend before deciding — jax.distributed
    rejects initialization after any backend query (jax.devices,
    jax.process_count, any computation), so the already-initialized check
    uses jax.distributed.is_initialized(), not jax.process_count().
    """
    if _is_initialized():
        return
    explicit = coordinator_address is not None
    # Opt-in env gate (NVS3D_MULTIHOST=1) rather than sniffing TPU_* vars:
    # single-host TPU containers may set TPU_WORKER_HOSTNAMES themselves.
    auto_tpu = os.environ.get("NVS3D_MULTIHOST") == "1"
    if explicit or auto_tpu:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )


def probe_backend(timeout_s: float = 45.0,
                  require_accelerator: bool = False,
                  env: Optional[dict] = None) -> Tuple[bool, str]:
    """Bounded reachability probe of the default JAX backend.

    Runs a REAL tiny computation with a host fetch in a DISPOSABLE child
    process (promoted from bench.py/tools: a wedged remote-accelerator
    tunnel has been observed passing backend init yet hanging on the first
    execution, and a process stuck in that IO enters uninterruptible sleep
    — SIGKILL doesn't reap it until the syscall returns, so the child is
    abandoned, never reaped in-process). Returns (ok, reason); never
    raises, never hangs past ~timeout_s.

    `require_accelerator=True` additionally rejects a probe that answered
    on CPU (the watcher semantics: CPU output is not TPU evidence).
    `env` overrides the child's environment (e.g. the tools watcher pops
    JAX_PLATFORMS so an ambient CPU pin doesn't shadow the accelerator).

    Drill hooks (tier-1 tests exercise the full Popen/timeout machinery
    without a real tunnel): NVS3D_FI_PROBE_HANG=1 makes the child sleep
    forever, NVS3D_FI_PROBE_FAIL=1 makes it exit non-zero.
    """
    if os.environ.get("NVS3D_FI_PROBE_HANG") == "1":
        code = "import time; time.sleep(3600)"
    elif os.environ.get("NVS3D_FI_PROBE_FAIL") == "1":
        code = "import sys; sys.exit(1)"
    else:
        code = ("import jax, jax.numpy as jnp; "
                "print(float(jnp.ones((8, 8)).sum()), "
                "jax.devices()[0].platform)")
    proc = subprocess.Popen(
        [sys.executable, "-c", code], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        out, _ = proc.communicate(timeout=max(1.0, timeout_s))
    except subprocess.TimeoutExpired:
        proc.kill()  # best effort; deliberately not reaped (see above)
        return False, f"probe timed out after {timeout_s:.0f}s (backend " \
                      "wedged: computation never returned)"
    out = (out or "").strip()
    if proc.returncode != 0:
        return False, f"probe exited rc={proc.returncode}"
    if require_accelerator and "cpu" in out:
        return False, f"probe answered on CPU ({out!r}), not an accelerator"
    return True, out


def require_backend(budget_s: Optional[float] = None,
                    try_s: Optional[float] = None,
                    default_budget_s: float = 45.0,
                    require_accelerator: bool = False) -> None:
    """probe_backend with retry across a budget; SystemExit(3) if dead.

    The structured replacement for the 360 s+ silent hangs of BENCH_r01-r05:
    an unreachable backend becomes a sub-minute (at the default budget)
    diagnosis — one reason line on stderr plus exit code
    EXIT_BACKEND_UNREACHABLE — instead of a wedged process an external
    watcher has to kill. Retries within the budget because the tunnel has
    been observed recovering in bursts.

    Knobs: NVS3D_PROBE_BUDGET_S (total; default `default_budget_s`),
    NVS3D_PROBE_TRY_S (per attempt, default min(45, budget)). An explicit
    JAX_PLATFORMS=cpu skips the probe entirely — CPU was requested and is
    always reachable.
    """
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return
    if budget_s is None:
        budget_s = float(os.environ.get("NVS3D_PROBE_BUDGET_S",
                                        default_budget_s))
    if try_s is None:
        try_s = float(os.environ.get("NVS3D_PROBE_TRY_S",
                                     min(45.0, budget_s)))
    deadline = time.monotonic() + budget_s
    attempt = 0
    reason = "no probe attempted"
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        ok, reason = probe_backend(min(try_s, max(5.0, remaining)),
                                   require_accelerator=require_accelerator)
        if ok:
            return
        if time.monotonic() >= deadline:
            break
        print(f"note: backend probe attempt {attempt} failed ({reason}); "
              f"retrying ({deadline - time.monotonic():.0f}s of budget "
              "left)", file=sys.stderr)
        time.sleep(min(10.0, max(0.0, deadline - time.monotonic())))
    print(f"error: default backend unreachable within {budget_s:.0f}s "
          f"({attempt} probe attempt(s); last: {reason}). Set "
          "JAX_PLATFORMS=cpu for a CPU run, or fix the accelerator "
          "tunnel.", file=sys.stderr)
    global LAST_FAILURE_REASON
    LAST_FAILURE_REASON = (f"backend unreachable within {budget_s:.0f}s "
                           f"({attempt} attempt(s); last: {reason})")
    raise SystemExit(EXIT_BACKEND_UNREACHABLE)


def process_shard(n: int) -> tuple[int, int]:
    """(shard_index, shard_count) for per-host data sharding of n records."""
    del n
    return jax.process_index(), jax.process_count()


def local_batch_size(global_batch_size: int) -> int:
    count = jax.process_count()
    if global_batch_size % count != 0:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by "
            f"{count} processes")
    return global_batch_size // count
