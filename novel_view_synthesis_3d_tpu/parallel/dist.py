"""Multi-host bring-up (SURVEY.md §2.3: the reference is single-host only —
`jax.device_count()` over local GPUs, no process coordination).

On TPU pods each host runs the same program; `jax.distributed.initialize`
wires the processes together (DCN for control, ICI for collectives). On
single-host (or under tests) this is a no-op.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def _is_initialized() -> bool:
    """jax.distributed.is_initialized() with a fallback for jax builds that
    predate it (< 0.5): the distributed client handle in jax._src is the
    same thing the public accessor reads. Still backend-free either way."""
    if hasattr(jax.distributed, "is_initialized"):
        return bool(jax.distributed.is_initialized())
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Initialize multi-process JAX if we're in a multi-host environment.

    On Cloud TPU VMs `jax.distributed.initialize()` auto-discovers the pod
    topology from the metadata server; explicit args cover other clusters.
    Safe to call unconditionally: single-process environments skip init.

    NOTE: must not touch the XLA backend before deciding — jax.distributed
    rejects initialization after any backend query (jax.devices,
    jax.process_count, any computation), so the already-initialized check
    uses jax.distributed.is_initialized(), not jax.process_count().
    """
    if _is_initialized():
        return
    explicit = coordinator_address is not None
    # Opt-in env gate (NVS3D_MULTIHOST=1) rather than sniffing TPU_* vars:
    # single-host TPU containers may set TPU_WORKER_HOSTNAMES themselves.
    auto_tpu = os.environ.get("NVS3D_MULTIHOST") == "1"
    if explicit or auto_tpu:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )


def process_shard(n: int) -> tuple[int, int]:
    """(shard_index, shard_count) for per-host data sharding of n records."""
    del n
    return jax.process_index(), jax.process_count()


def local_batch_size(global_batch_size: int) -> int:
    count = jax.process_count()
    if global_batch_size % count != 0:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by "
            f"{count} processes")
    return global_batch_size // count
