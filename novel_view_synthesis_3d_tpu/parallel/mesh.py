"""Device mesh + sharding helpers (the TPU-native distributed substrate).

Replaces the reference's `jax.pmap` data-parallel path, which SURVEY.md §2.3
shows to be degenerate: it replicates the SAME batch to every device
(train.py:132-140), declares `axis_name='ensemble'` but never emits a
collective (gradients are never averaged), and gives each device a different
init (train.py:122-123) — an unsynchronized ensemble, not DP.

Here:
  - one global `Mesh` with axes ('data', 'model', 'seq');
  - the batch is SHARDED over 'data' (per-device micro-batches);
  - params/opt-state are replicated (NamedSharding(P())); under `jit`,
    autodiff of the mean loss over the sharded batch makes XLA emit the
    gradient all-reduce (psum) over ICI automatically;
  - 'model' is reserved for tensor parallelism, 'seq' feeds ring attention
    (parallel/ring_attention.py).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from novel_view_synthesis_3d_tpu.config import MeshConfig

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"


def fit_local_mesh(config: Optional[MeshConfig] = None
                   ) -> Optional[Mesh]:
    """Mesh over the LOCAL device count, ignoring the config's data claim.

    For tools (eval CLI, benches) that reuse a *training* config on whatever
    host they run on: keeps model/seq claims but recomputes the data axis as
    n_devices // (model×seq). Returns None — caller falls back to the
    default device — when the devices don't divide the model×seq claims (a
    training mesh like data=32 must not crash a 1-chip eval) or in
    multi-process runs (these tools assemble full host-side batches, which
    only a single-process mesh can shard safely).
    """
    config = config or MeshConfig()
    if jax.process_count() > 1:
        _warn_fallback("multi-process run: falling back to the default "
                       "device (host-side batches can't shard a global mesh)")
        return None
    n = len(jax.devices())
    claims = max(1, config.model) * max(1, config.seq)
    if n % claims != 0:
        _warn_fallback(
            f"{n} local device(s) not divisible by the config's "
            f"model×seq = {claims}: falling back to the default device — "
            "this run is UNSHARDED despite the sharded config")
        return None
    import dataclasses

    if config.data not in (-1, n // claims):
        _warn_fallback(
            f"config mesh.data={config.data} replaced by {n // claims} "
            f"(all {n} local devices minus model×seq = {claims} claims)")
    return make_mesh(dataclasses.replace(config, data=n // claims))


def _warn_fallback(msg: str) -> None:
    """Mesh-fit decisions must be LOUD: a bench/eval that silently drops its
    sharded-mesh request would report single-device numbers under a sharded
    label (VERDICT r2 weak #5). Printed to stderr and sent through warnings
    so tools and test harnesses both see it."""
    import sys
    import warnings

    warnings.warn(f"fit_local_mesh: {msg}", stacklevel=3)
    print(f"warning: fit_local_mesh: {msg}", file=sys.stderr)


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the global device mesh.

    `data=-1` absorbs all devices not claimed by the other axes. Works for
    single chip (1×1×1), one host with N devices, and multi-host slices
    (pass `jax.devices()` after `jax.distributed.initialize`).
    """
    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    model = max(1, config.model)
    seq = max(1, config.seq)
    data = config.data
    if data == -1:
        if n % (model * seq) != 0:
            raise ValueError(
                f"{n} devices not divisible by model×seq = {model * seq}")
        data = n // (model * seq)
    if data * model * seq > n:
        raise ValueError(
            f"mesh {data}×{model}×{seq} > {n} available devices")
    # An explicit smaller mesh uses a device subset (handy for tests and for
    # carving a slice out of a shared host) — but only single-process: on a
    # multi-host slice the trailing hosts' devices would be silently dropped
    # and their shard_batch calls would target a mesh they aren't part of.
    if data * model * seq < n and jax.process_count() > 1:
        raise ValueError(
            f"mesh {data}×{model}×{seq} uses a subset of the {n} devices, "
            "which is not supported in multi-process runs")
    arr = np.asarray(devices[: data * model * seq]).reshape(data, model, seq)
    return Mesh(arr, axis_names=(DATA_AXIS, MODEL_AXIS, SEQ_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis (batch) sharding over the 'data' mesh axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def stacked_batch_sharding(mesh: Mesh) -> NamedSharding:
    """(K, B, ...) multi-step batches (train.steps_per_dispatch): the K
    step axis is replicated (lax.scan consumes it sequentially), B shards
    over 'data' exactly like a single batch."""
    return NamedSharding(mesh, P(None, DATA_AXIS))


def shard_batch(mesh: Mesh, batch, stacked: bool = False):
    """Move a host-side batch pytree onto the mesh, sharded over 'data'.

    Single-process: a plain device_put with a NamedSharding. Multi-process:
    each process contributes its LOCAL shard of the global batch via
    `jax.make_array_from_process_local_data` (per-host Grain shards feed
    this — SURVEY.md §2.3 "TPU-native equivalents"). `stacked` marks a
    (K, B, ...) multi-step batch (leading step axis replicated).
    """
    sharding = stacked_batch_sharding(mesh) if stacked else batch_sharding(mesh)
    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)),
        batch,
    )


def replicate(mesh: Mesh, tree):
    """Replicate a pytree (params/opt state) across the whole mesh."""
    return jax.device_put(tree, replicated(mesh))


# ---------------------------------------------------------------------------
# FSDP / ZeRO-style parameter sharding
# ---------------------------------------------------------------------------
# The reference replicates the full params + optimizer state on every device
# (train.py:46 — SURVEY.md §2.3 "FSDP: No"). Here large tensors are sharded
# over the 'data' axis: under jit, XLA inserts the all-gather before use and
# the reduce-scatter on the gradient — the standard JAX FSDP recipe
# (sharding-annotation-driven, no hand-written collectives).

def _largest_divisible_axis(shape, n: int, taken=()) -> int:
    """Index of the largest axis divisible by n, excluding `taken`; -1 if none."""
    best = -1
    for i, d in enumerate(shape):
        if i not in taken and d % n == 0 and (best == -1 or d > shape[best]):
            best = i
    return best


def fsdp_spec(mesh: Mesh, shape, min_elems: int = 2 ** 15) -> P:
    """PartitionSpec sharding the largest 'data'-divisible axis of `shape`.

    Small tensors (biases, norm scales, scalars) stay replicated — sharding
    them costs more in collective latency than it saves in HBM.
    """
    n = mesh.shape[DATA_AXIS]
    if n <= 1 or int(np.prod(shape or (1,))) < min_elems:
        return P()
    best = _largest_divisible_axis(shape, n)
    if best == -1:
        return P()
    spec = [None] * len(shape)
    spec[best] = DATA_AXIS
    return P(*spec)


# ---------------------------------------------------------------------------
# Tensor parallelism over the 'model' axis
# ---------------------------------------------------------------------------
# Weight-stationary output-channel sharding (Megatron column-parallel style),
# driven purely by sharding annotations — GSPMD inserts the collectives:
#   - attention q/k/v DenseGeneral kernels (C, heads, head_dim): heads axis
#     sharded → each model-shard computes its own heads;
#   - conv / dense kernels (..., Cin, Cout): Cout sharded → channel-sharded
#     activations, all-gathered where a consumer needs the full channels;
#   - matching biases sharded on the same output axis; norm scales and other
#     small vectors replicated.
# The reference has no TP at all (SURVEY.md §2.3 "Tensor parallel: No").

def _path_names(path) -> list:
    names = []
    for entry in path:
        for attr in ("key", "name", "idx"):
            if hasattr(entry, attr):
                names.append(str(getattr(entry, attr)))
                break
    return names


def tp_spec(path_names, shape, tp_n: int) -> Optional[list]:
    """Partition-axis list for one param under TP, or None if replicated."""
    if tp_n <= 1 or not shape or not path_names:
        return None
    leaf = path_names[-1]
    parent = next((p for p in reversed(path_names[:-1])
                   if not p.isdigit()), "")
    spec = [None] * len(shape)
    if parent.startswith("DenseGeneral"):
        if leaf == "kernel" and len(shape) == 3:
            # q/k/v kernel (C, heads, hd) — C factors into heads·hd — is
            # column-parallel on heads; the out-projection kernel
            # (heads, hd, C) — C on the last axis — is row-parallel on its
            # heads contraction (partial outputs psum'd by GSPMD), so the
            # head-sharded attention output feeds it with no reshard.
            if shape[2] == shape[0] * shape[1] and shape[0] % tp_n == 0:
                spec[0] = MODEL_AXIS
                return spec
            if shape[0] == shape[1] * shape[2] and shape[1] % tp_n == 0:
                spec[1] = MODEL_AXIS
                return spec
            return None
        if leaf == "bias" and len(shape) == 2 and shape[0] % tp_n == 0:
            spec[0] = MODEL_AXIS  # q/k/v bias (heads, hd)
            return spec
        return None  # out-proj bias (C,) rides the psum'd output: replicate
    if leaf == "kernel" and len(shape) >= 2 and shape[-1] % tp_n == 0:
        spec[-1] = MODEL_AXIS
        return spec
    # Only biases of output-channel-sharded layers follow their kernel; norm
    # scales/biases and other small vectors stay replicated.
    if (leaf == "bias" and len(shape) == 1 and shape[0] % tp_n == 0
            and (parent.startswith("Conv") or parent.startswith("Dense"))):
        spec[0] = MODEL_AXIS
        return spec
    return None


def param_spec(mesh: Mesh, path_names, shape, fsdp: bool, tp: bool,
               min_elems: int = 2 ** 15) -> P:
    """Combined TP ('model' axis) + FSDP ('data' axis) spec for one leaf."""
    spec = (tp_spec(path_names, shape, mesh.shape[MODEL_AXIS])
            if tp else None)
    if spec is None:
        spec = [None] * len(shape)
    if fsdp:
        n = mesh.shape[DATA_AXIS]
        if n > 1 and int(np.prod(shape or (1,))) >= min_elems:
            taken = tuple(i for i, s in enumerate(spec) if s is not None)
            best = _largest_divisible_axis(shape, n, taken)
            if best != -1:
                spec[best] = DATA_AXIS
    if all(s is None for s in spec):
        return P()
    return P(*spec)


def state_shardings(mesh: Mesh, state, fsdp: bool, tp: bool = False):
    """Sharding pytree for a TrainState.

    fsdp=False, tp=False → fully replicated. fsdp → largest-divisible-axis
    sharding over 'data' (ZeRO-3). tp (with mesh.model > 1) → name-aware
    head/output-channel sharding over 'model'; both compose per leaf.
    """
    tp = tp and mesh.shape[MODEL_AXIS] > 1
    if not fsdp and not tp:
        return replicated(mesh)
    if not tp:
        return jax.tree.map(
            lambda x: NamedSharding(mesh, fsdp_spec(mesh, jnp_shape(x))),
            state)
    return jax.tree_util.tree_map_with_path(
        lambda path, x: NamedSharding(
            mesh, param_spec(mesh, _path_names(path), jnp_shape(x),
                             fsdp, True)),
        state)


def jnp_shape(x):
    return tuple(getattr(x, "shape", ()) or ())


def num_data_shards(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS]


def divides_data_axis(mesh: Optional[Mesh], n: int) -> bool:
    """True when a batch of n rows can shard evenly over the 'data' axis.

    The serving micro-batcher (sample/service.py) uses this to pick its
    bucket ladder: buckets that divide the data axis dispatch through
    `shard_batch` (one coalesced batch served data-parallel across the
    mesh); anything else would leave ragged shards, so those buckets
    dispatch replicated over the mesh instead (params are committed to
    the mesh's device set, so a single-device fallback would hand jit
    incompatible placements) rather than crash mid-serve."""
    return mesh is not None and n % num_data_shards(mesh) == 0


def validate_global_batch(mesh: Mesh, global_batch_size: int) -> None:
    n = num_data_shards(mesh)
    if global_batch_size % n != 0:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by data-axis "
            f"size {n}")


def tree_device_bytes(tree) -> int:
    """Per-device bytes of a pytree's leaves (0 for an empty/None tree).

    Sharded leaves count their LOCAL shard shape (leaf.sharding), so the
    same params tree reports full bytes when replicated and 1/N when ZeRO-
    or FSDP-sharded — this feeds the nvs3d_*_bytes gauges and the bench
    memory breakdown, where "what actually sits on one chip" is the
    number that decides whether a config fits."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            continue
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            try:
                shape = sharding.shard_shape(tuple(shape))
            except (TypeError, ValueError):
                pass
        dtype = getattr(leaf, "dtype", None)
        itemsize = np.dtype(dtype).itemsize if dtype is not None else 4
        total += int(np.prod(shape or (1,))) * itemsize
    return total
