"""Device mesh + sharding helpers (the TPU-native distributed substrate).

Replaces the reference's `jax.pmap` data-parallel path, which SURVEY.md §2.3
shows to be degenerate: it replicates the SAME batch to every device
(train.py:132-140), declares `axis_name='ensemble'` but never emits a
collective (gradients are never averaged), and gives each device a different
init (train.py:122-123) — an unsynchronized ensemble, not DP.

Here:
  - one global `Mesh` with axes ('data', 'model', 'seq');
  - the batch is SHARDED over 'data' (per-device micro-batches);
  - params/opt-state are replicated (NamedSharding(P())); under `jit`,
    autodiff of the mean loss over the sharded batch makes XLA emit the
    gradient all-reduce (psum) over ICI automatically;
  - 'model' is reserved for tensor parallelism, 'seq' feeds ring attention
    (parallel/ring_attention.py).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from novel_view_synthesis_3d_tpu.config import MeshConfig

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the global device mesh.

    `data=-1` absorbs all devices not claimed by the other axes. Works for
    single chip (1×1×1), one host with N devices, and multi-host slices
    (pass `jax.devices()` after `jax.distributed.initialize`).
    """
    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    model = max(1, config.model)
    seq = max(1, config.seq)
    data = config.data
    if data == -1:
        if n % (model * seq) != 0:
            raise ValueError(
                f"{n} devices not divisible by model×seq = {model * seq}")
        data = n // (model * seq)
    if data * model * seq > n:
        raise ValueError(
            f"mesh {data}×{model}×{seq} > {n} available devices")
    # An explicit smaller mesh uses a device subset (handy for tests and for
    # carving a slice out of a shared host) — but only single-process: on a
    # multi-host slice the trailing hosts' devices would be silently dropped
    # and their shard_batch calls would target a mesh they aren't part of.
    if data * model * seq < n and jax.process_count() > 1:
        raise ValueError(
            f"mesh {data}×{model}×{seq} uses a subset of the {n} devices, "
            "which is not supported in multi-process runs")
    arr = np.asarray(devices[: data * model * seq]).reshape(data, model, seq)
    return Mesh(arr, axis_names=(DATA_AXIS, MODEL_AXIS, SEQ_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis (batch) sharding over the 'data' mesh axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch):
    """Move a host-side batch pytree onto the mesh, sharded over 'data'.

    Single-process: a plain device_put with a NamedSharding. Multi-process:
    each process contributes its LOCAL shard of the global batch via
    `jax.make_array_from_process_local_data` (per-host Grain shards feed
    this — SURVEY.md §2.3 "TPU-native equivalents").
    """
    sharding = batch_sharding(mesh)
    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)),
        batch,
    )


def replicate(mesh: Mesh, tree):
    """Replicate a pytree (params/opt state) across the whole mesh."""
    return jax.device_put(tree, replicated(mesh))


# ---------------------------------------------------------------------------
# FSDP / ZeRO-style parameter sharding
# ---------------------------------------------------------------------------
# The reference replicates the full params + optimizer state on every device
# (train.py:46 — SURVEY.md §2.3 "FSDP: No"). Here large tensors are sharded
# over the 'data' axis: under jit, XLA inserts the all-gather before use and
# the reduce-scatter on the gradient — the standard JAX FSDP recipe
# (sharding-annotation-driven, no hand-written collectives).

def fsdp_spec(mesh: Mesh, shape, min_elems: int = 2 ** 15) -> P:
    """PartitionSpec sharding the largest 'data'-divisible axis of `shape`.

    Small tensors (biases, norm scales, scalars) stay replicated — sharding
    them costs more in collective latency than it saves in HBM.
    """
    n = mesh.shape[DATA_AXIS]
    if n <= 1 or int(np.prod(shape or (1,))) < min_elems:
        return P()
    best = -1
    for i, d in enumerate(shape):
        if d % n == 0 and (best == -1 or d > shape[best]):
            best = i
    if best == -1:
        return P()
    spec = [None] * len(shape)
    spec[best] = DATA_AXIS
    return P(*spec)


def state_shardings(mesh: Mesh, state, fsdp: bool):
    """Sharding pytree for a TrainState: fsdp=False → fully replicated;
    fsdp=True → per-leaf largest-axis sharding over 'data'."""
    if not fsdp:
        return replicated(mesh)
    return jax.tree.map(
        lambda x: NamedSharding(mesh, fsdp_spec(mesh, jnp_shape(x))), state)


def jnp_shape(x):
    return tuple(getattr(x, "shape", ()) or ())


def num_data_shards(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS]


def validate_global_batch(mesh: Mesh, global_batch_size: int) -> None:
    n = num_data_shards(mesh)
    if global_batch_size % n != 0:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by data-axis "
            f"size {n}")
