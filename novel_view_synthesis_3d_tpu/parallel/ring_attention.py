"""Ring attention: sequence-parallel exact attention over the 'seq' mesh axis.

The reference has no long-sequence machinery (SURVEY.md §5.7 — its attention
runs on ≤1024 tokens). Scaling this domain means higher image resolution
(256² ⇒ 65k tokens if attention were enabled at fine resolutions) and k>1
frames (more cross-attention pairs). This module makes that a first-class
capability: the H·W token axis is sharded over the mesh 'seq' axis, each
device holds one query block, and key/value blocks rotate around the ring via
`jax.lax.ppermute` (ICI neighbor exchange) while a numerically-stable online
softmax accumulates the output — compute and communication overlap, peak
memory is O(L·L/n) per device, and the result is EXACT attention.

Layout: q, k, v are (B, L_local, H, D); the accumulator runs in float32.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from novel_view_synthesis_3d_tpu.parallel.mesh import SEQ_AXIS

_NEG_INF = -1e30


def _shard_map(f, mesh, in_specs, out_specs):
    """jax.shard_map with a fallback for jax builds that predate its
    top-level promotion (< 0.6): jax.experimental.shard_map is the same
    transform with the replication check under its older name."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def _block_update(q, k, v, m_prev, l_prev, o_prev, scale):
    """One flash-attention style block accumulation step.

    q: (B, Lq, H, D) · k, v: (B, Lk, H, D)
    m, l: (B, H, Lq) running max / normalizer · o: (B, Lq, H, D) f32.
    """
    s = jnp.einsum("blhd,bmhd->bhlm", q, k,
                   preferred_element_type=jnp.float32) * scale
    m_cur = jnp.maximum(m_prev, s.max(axis=-1))
    corr = jnp.exp(m_prev - m_cur)
    p = jnp.exp(s - m_cur[..., None])  # (B, H, Lq, Lk)
    l_cur = l_prev * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhlm,bmhd->blhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    o_cur = o_prev * corr.transpose(0, 2, 1)[..., None] + pv
    return m_cur, l_cur, o_cur


def ring_self_attention_local(q, k, v, *, axis_name: str = SEQ_AXIS,
                              scale: Optional[float] = None):
    """Per-shard body (call inside shard_map over `axis_name`)."""
    B, L, H, D = q.shape
    scale = (D ** -0.5) if scale is None else scale
    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    m0 = jnp.full((B, H, L), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, L), jnp.float32)
    o0 = jnp.zeros((B, L, H, D), jnp.float32)

    def body(_, carry):
        m, l, o, k_blk, v_blk = carry
        m, l, o = _block_update(q, k_blk, v_blk, m, l, o, scale)
        # Rotate k/v to the next ring neighbor while the next block computes.
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return m, l, o, k_blk, v_blk

    m, l, o, _, _ = jax.lax.fori_loop(0, n, body, (m0, l0, o0, k, v))
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_self_attention(q, k, v, mesh: Mesh, *, axis_name: str = SEQ_AXIS,
                        scale: Optional[float] = None,
                        batch_axis: Optional[str] = None):
    """Exact attention with the token axis sharded over `axis_name`.

    q, k, v: GLOBAL (B, L, H, D) arrays (sharded or shardable); returns the
    attention output with the same global shape/sharding. `batch_axis`
    additionally shards the batch dim (composes SP with DP inside one
    shard_map — the train-step layout where batch rides the 'data' axis).
    """
    spec = P(batch_axis, axis_name, None, None)
    fn = _shard_map(
        partial(ring_self_attention_local, axis_name=axis_name, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return fn(q, k, v)
