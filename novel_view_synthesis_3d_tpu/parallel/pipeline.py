"""GPipe pipeline parallelism for the XUNet over the mesh 'model' axis.

`mesh.stages = S > 1` partitions the XUNet's ordered op list
(models/xunet.py `pipeline_op_specs`) into S contiguous stages, one per
'model'-axis shard, and streams the `train.grad_accum_steps` micro-batches
through a fill/drain schedule (Huang et al. 2019, GPipe — PAPERS.md):

      tick t:   stage s runs micro-batch m = t - s   (valid for 0 <= m < M)
                then hands its boundary activations to stage s+1 via
                jax.lax.ppermute — one ICI neighbor hop, no all-to-all.

  T = M + S - 1 ticks total; (S-1)/T of stage-ticks are fill/drain bubble
  (`bubble_fraction`). Each device runs ONLY its stage's ops on one
  micro-batch of activations at a time — the live-activation footprint
  per device drops to one stage slice of one micro-batch, which is what
  lets the training step grow past one chip's activation memory.

Mechanics (all inside one shard_map over ('model', 'data')):

  - Params enter replicated (in_spec P()) — matching the repo's
    replicated-params training layout (update sharding is ZeRO's job,
    parallel/zero.py) — and each stage's switch branch touches only its
    own op range's param subtree (`pipeline_op_specs` names). What the
    pipeline shards is the ACTIVATION footprint: each device holds one
    stage × one micro-batch of activations instead of the whole net.
    The replicated feed keeps reverse-mode AD trivial: the transpose is
    a psum over ('model', 'data') that assembles the full gradient tree
    with no hand-written collectives. (A per-stage packed param stack
    with in_spec P('model', None) is the memory-leaner layout, but jit's
    sharding propagation mis-partitions the pack→shard_map handoff on
    this jax version — values produced INSIDE the jit that feed a
    'model'-split in_spec come out wrong, while the identical array
    passed as a jit argument works. Revisit when jax is bumped.)
  - Boundary activations (h, skip stack, logsnr_emb, pose_embs) are
    flattened to one padded f32 vector per boundary — a single static
    carry shape lets every stage run the same lax.scan program. Shapes
    per boundary come from jax.eval_shape of the prefix slice at trace
    time; nothing is shape-polymorphic at runtime.
  - lax.switch on axis_index('model') picks the stage body; idle
    (fill/drain) ticks run the stage on zeros — every op is finite on
    zeros, and the last stage masks invalid outputs to 0 so idle compute
    contributes exactly zero cotangent.
  - The diffusion micro-batch DERIVATION (t, noise, z, cond_mask, …)
    also runs inside the shard_map, via the `derive_local` callback:
    every shard redraws the full-batch randoms from the replicated step
    key and slices its own global row block — bit-identical to the
    sequential path's global draws, at the cost of a B-sized (instead of
    B/D-sized) PRNG draw per shard, which is noise-tensor sized and
    negligible next to one XUNet stage. This is the second partitioner
    workaround: on this jax version, jax.random draws whose consumers
    are 'data'-sharded come out with WRONG VALUES on meshes with a
    nontrivial 'model' axis (the key is identical; the generated bits
    are not) — inside shard_map each shard compiles single-device code
    and the bug cannot trigger. Revisit when jax is bumped.
  - Predictions stay inside: the region returns per-micro-batch LOCAL
    mean losses, out-sharded P(None, 'data') as an (M, data) grid; the
    caller's global mean equals the sequential path's loss because micro
    slices and data shards are all equal-sized.

The dropout key for micro-batch m is shared by all stages; flax folds it
per module path, and `pipeline_op_specs` pins explicit module names, so a
stage slice draws the SAME masks as the monolithic forward — pipelined
training is numerically the accumulation path up to f32 reduction order
(tests/test_pipeline.py asserts equivalence for S in {2, 4}). Note the
row→micro-batch grouping differs from the sequential path (each data
shard splits its OWN rows into M micros); per-row t/noise/cond_mask pairs
are identical, and with equal-sized micros the mean-of-means is the same
global mean, so loss and grads still agree.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from novel_view_synthesis_3d_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

# Keys of the model-input slice of a training micro-batch (train/step.py
# builds micro dicts with these + cond_mask + regression_target [+
# loss_weight]; only these and cond_mask enter model.apply).
MODEL_KEYS = ("x", "z", "logsnr", "R1", "t1", "R2", "t2", "K")


def stage_bounds(num_ops: int, stages: int) -> List[int]:
    """Contiguous op partition: S+1 boundaries, every stage non-empty.

    Even op-count split (first `num_ops % stages` stages take one extra).
    Deterministic in (num_ops, stages) alone so every host and every
    trace agrees on the partition without coordination."""
    if stages < 1:
        raise ValueError(f"stages must be >= 1, got {stages}")
    if num_ops < stages:
        raise ValueError(
            f"cannot split {num_ops} XUNet ops into {stages} pipeline "
            "stages — reduce mesh.stages (each stage needs >= 1 op)")
    base, rem = divmod(num_ops, stages)
    bounds = [0]
    for s in range(stages):
        bounds.append(bounds[-1] + base + (1 if s < rem else 0))
    return bounds


def bubble_fraction(num_micro: int, stages: int) -> float:
    """Fill/drain bubble share of the GPipe schedule: (S-1)/(M+S-1).

    Static in config — exported to obs gauges and the bench JSON so a
    too-coarse micro-batch split is visible before it burns a pod-day."""
    return (stages - 1) / max(1, num_micro + stages - 1)


def _tree_size(aval_tree) -> int:
    return sum(int(np.prod(a.shape or (1,)))
               for a in jax.tree_util.tree_leaves(aval_tree))


def _flatten_pad(tree, length: int) -> jnp.ndarray:
    """Pytree → one zero-padded f32 vector (linear, AD-transparent)."""
    leaves = jax.tree_util.tree_leaves(tree)
    flat = jnp.concatenate(
        [jnp.ravel(x).astype(jnp.float32) for x in leaves]
    ) if leaves else jnp.zeros((0,), jnp.float32)
    return jnp.pad(flat, (0, length - flat.shape[0]))


def _unflatten(vec: jnp.ndarray, aval_tree):
    """Padded f32 vector → pytree with the aval tree's shapes/dtypes."""
    leaves, treedef = jax.tree_util.tree_flatten(aval_tree)
    out, off = [], 0
    for a in leaves:
        size = int(np.prod(a.shape or (1,)))
        out.append(vec[off:off + size].reshape(a.shape).astype(a.dtype))
        off += size
    return jax.tree_util.tree_unflatten(treedef, out)


def _aval_tree(tree):
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x)),
        tree)


def _stage_param_names(specs, bounds: Sequence[int]) -> List[Tuple[str, ...]]:
    names = []
    for s in range(len(bounds) - 1):
        ns: List[str] = []
        for _, info in specs[bounds[s]:bounds[s + 1]]:
            ns.extend(info["param_names"])
        names.append(tuple(ns))
    return names


def value_and_grad_pipelined(model, mesh: Mesh, stages: int, params,
                             batch, rng, micro_steps, derive_local,
                             micro_loss_of):
    """(mean loss over micro-batches, full param-tree grads), pipelined.

    model      XUNet whose __call__ honors ops=(a, b) slices.
    mesh       mesh with shape['model'] == stages.
    batch      raw training batch pytree, batch axis 0 sharded over 'data'
               (parallel.mesh.shard_batch layout).
    rng        step-folded PRNG key, replicated.
    micro_steps  M, the number of micro-batches per shard.
    derive_local  (local_batch, rng, data_index) -> (micro, keys); runs
               INSIDE the shard_map on one data shard's rows. micro is a
               pytree of (M, b_local, ...) arrays (MODEL_KEYS + cond_mask
               + regression_target [+ loss_weight]); keys is (M, 2)
               uint32 dropout keys. Must draw randoms full-batch from the
               replicated key and slice rows [d*B_l, (d+1)*B_l) so every
               row sees the sequential path's values (see module note on
               the partitioner bug).
    micro_loss_of  (pred, micro_batch_slice) -> scalar micro loss.

    Numerically equivalent to the sequential accumulation scan in
    train/step.py (same per-row t/noise/cond_mask, equal-size micro
    means) up to f32 reduction order.
    """
    if mesh.shape[MODEL_AXIS] != stages:
        raise ValueError(
            f"pipeline stages={stages} needs mesh 'model' axis of the same "
            f"size, got {mesh.shape[MODEL_AXIS]}")

    # Differentiate the whole (derive ∘ forward ∘ loss) composite wrt
    # params: the shard_map body and the ppermute handoffs are
    # AD-transparent, so one value_and_grad yields the full-tree gradient.
    def loss_of(p):
        losses = _pipelined_losses(model, mesh, stages, p, batch, rng,
                                   micro_steps, derive_local, micro_loss_of)
        return jnp.mean(losses)

    return jax.value_and_grad(loss_of)(params)


def _pipelined_losses(model, mesh: Mesh, stages: int, params, batch, rng,
                      micro_steps, derive_local, micro_loss_of):
    """Run M micro-batches through S stages; returns (M, data) per-micro
    local mean losses (data axis sharded over 'data')."""
    from novel_view_synthesis_3d_tpu.models.xunet import pipeline_op_specs
    from novel_view_synthesis_3d_tpu.parallel.ring_attention import (
        _shard_map)

    S = stages
    M = int(micro_steps)
    T = M + S - 1
    specs = pipeline_op_specs(model.config)
    bounds = stage_bounds(len(specs), S)
    stage_names = _stage_param_names(specs, bounds)

    data_n = mesh.shape[DATA_AXIS]
    B = batch["target"].shape[0]
    if B % (data_n * M) != 0:
        raise ValueError(
            f"global batch {B} not divisible by data axis x micro steps "
            f"({data_n} x {M})")
    b_shard = B // data_n        # rows per data shard
    b_local = b_shard // M       # rows per (data shard, micro-batch)

    # --- trace-time geometry ------------------------------------------------
    # Derive the micro avals by eval_shape'ing the caller's derivation on
    # one data shard's row block — no FLOPs, and geometry stays in sync
    # with whatever fields the caller derives.
    local_batch_aval = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct((b_shard,) + a.shape[1:], a.dtype),
        batch)
    micro_aval, keys_aval = jax.eval_shape(
        derive_local, local_batch_aval, _aval_tree(rng),
        jax.ShapeDtypeStruct((), jnp.int32))
    mb_aval = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype),
        {k: micro_aval[k] for k in MODEL_KEYS})
    cm_aval = jax.ShapeDtypeStruct((b_local,), micro_aval["cond_mask"].dtype)
    key_aval = jax.ShapeDtypeStruct(tuple(keys_aval.shape[1:]),
                                    keys_aval.dtype)
    param_avals = _aval_tree(params)

    def _prefix(p, mb, cm, k, upto):
        return model.apply({"params": p}, mb, cond_mask=cm, train=True,
                           ops=(0, upto), rngs={"dropout": k})

    # Boundary activation avals: carry entering stage s is the output of
    # the prefix slice [0, bounds[s]).  eval_shape costs no FLOPs.
    boundary_avals = [
        jax.eval_shape(partial(_prefix, upto=bounds[s]),
                       param_avals, mb_aval, cm_aval, key_aval)
        for s in range(1, S)
    ]
    Lc = max(_tree_size(av) for av in boundary_avals)

    pred_shape = (b_local,) + tuple(micro_aval["z"].shape[2:])

    def body(p_full, local_batch, rng_in):
        s_idx = jax.lax.axis_index(MODEL_AXIS)
        micro_local, keys_local = derive_local(
            local_batch, rng_in, jax.lax.axis_index(DATA_AXIS))

        def pick_micro(m):
            return jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, m, 0,
                                                       keepdims=False),
                micro_local)

        def make_branch(s):
            a, b = bounds[s], bounds[s + 1]
            sub = {n: p_full[n] for n in stage_names[s]}

            def branch(vec_in, t):
                m = jnp.clip(t - s, 0, M - 1)
                valid = ((t >= s) & (t - s < M)).astype(jnp.float32)
                mb = pick_micro(m)
                key = jax.lax.dynamic_index_in_dim(keys_local, m, 0,
                                                   keepdims=False)
                # Inside shard_map the dropout mask is drawn PER data
                # shard (the global-mask GSPMD semantics of the scan path
                # don't apply); folding the shard index in keeps masks
                # decorrelated across 'data'. Consequence: pipelined runs
                # match the sequential path bit-for-bit only at
                # dropout=0 — with dropout on they are statistically,
                # not numerically, equivalent.
                key = jax.random.fold_in(
                    key, jax.lax.axis_index(DATA_AXIS))
                model_mb = {k: mb[k] for k in MODEL_KEYS}
                carry = (None if s == 0
                         else _unflatten(vec_in, boundary_avals[s - 1]))
                out = model.apply({"params": sub}, model_mb,
                                  cond_mask=mb["cond_mask"], train=True,
                                  ops=(a, b), carry=carry,
                                  rngs={"dropout": key})
                if s == S - 1:
                    # Final slice returns the prediction; idle ticks are
                    # masked to exact zeros so fill/drain compute carries
                    # zero cotangent.
                    pred = out.astype(jnp.float32) * valid
                    return jnp.zeros((Lc,), jnp.float32), pred
                return _flatten_pad(out, Lc), jnp.zeros(pred_shape,
                                                        jnp.float32)

            return branch

        branches = [make_branch(s) for s in range(S)]

        def tick(vec, t):
            vec_out, pred = jax.lax.switch(s_idx, branches, vec, t)
            # Stage s's tick-t output reaches stage s+1 for tick t+1; the
            # last stage sends nothing, stage 0 receives zeros (ignored).
            vec_out = jax.lax.ppermute(
                vec_out, MODEL_AXIS,
                perm=[(i, i + 1) for i in range(S - 1)])
            return vec_out, pred

        _, preds = jax.lax.scan(tick, jnp.zeros((Lc,), jnp.float32),
                                jnp.arange(T))
        # Only the last stage's rows are nonzero; psum replicates them
        # across 'model' so every shard computes the same local losses.
        preds = jax.lax.psum(preds, MODEL_AXIS)
        # Micro-batch m finishes the last stage at tick m + S - 1.
        preds = preds[S - 1:S - 1 + M]
        losses = jax.vmap(micro_loss_of)(preds, micro_local)
        return losses.reshape(M, 1)

    batch_specs = jax.tree_util.tree_map(
        lambda a: P(DATA_AXIS), batch)
    param_specs = jax.tree_util.tree_map(lambda a: P(), params)
    out = _shard_map(
        body, mesh,
        in_specs=(param_specs, batch_specs, P()),
        out_specs=P(None, DATA_AXIS),
    )(params, batch, rng)
    return out
