"""ZeRO-style sharded weight update over the mesh 'data' axis.

`train.update_sharding='zero'` keeps params REPLICATED for forward/backward
(no per-layer all-gathers, unlike `train.fsdp`) but stores the Adam moments
and the EMA as 1/N shards per data replica — the layout of "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training" (Xu et
al. 2020, PAPERS.md). The step becomes:

      grads (replicated after XLA's DP all-reduce)
        │  shard_map over 'data': each replica slices row i of a
        ▼  lane-padded (N, c) view — XLA's reduce-scatter pass folds the
      grad shard (c,)        all-reduce + slice into one reduce-scatter
        │  Adam + EMA on the local 1/N shard (elementwise, so the shard
        ▼  update is bitwise the slice of the replicated update)
      param shard (c,)
        │  all_gather(tiled) over 'data'
        ▼
      fresh params (replicated again for the next fwd/bwd)

Leaf layout ("lane-friendly flatten/pad"): each float leaf with >=
`min_elems` elements is flattened, zero-padded to N·c with c a multiple of
128 (the TPU lane width, so every shard is a whole number of vregs), and
viewed as (N, c) sharded PartitionSpec('data', None). Small leaves (biases,
norm scales, scalar counts) stay replicated — sharding them costs more in
collective latency than the bytes saved. Padding lanes hold zeros and stay
zero under Adam (zero grad + zero moments → zero update), so they never
leak into real values.

The packed representation is what lives in TrainState.opt_state /
ema_params between steps (and is donated). Checkpoints stay in the
canonical UNPACKED layout — the Trainer gathers on save and re-packs on
restore — so a run can resume under a different update_sharding setting
bit-identically (tests/test_zero_checkpoint.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from novel_view_synthesis_3d_tpu.parallel.mesh import DATA_AXIS

# TPU vector lane width; shard rows padded to a multiple of this so each
# replica's slice is contiguous whole vregs (see /opt/skills/guides —
# min f32 tile is (8, 128)).
LANE = 128

# Leaves below this element count stay replicated. Matches the spirit of
# mesh.fsdp_spec's min_elems but lower: the packed layout can shard ANY
# large-enough leaf (no divisibility constraint), and the per-leaf cost is
# one slice + one all-gather row, so the break-even is earlier.
MIN_ELEMS = 1024


@dataclasses.dataclass(frozen=True)
class LeafPlan:
    """Static packing geometry for one pytree leaf.

    NOT a registered pytree node on purpose: a plan tree built with
    jax.tree.map(..., tree) has LeafPlan leaves and can be zipped against
    the data tree in later jax.tree.map calls.
    """

    packed: bool
    shape: Tuple[int, ...]
    dtype: Any
    rows: int  # data-axis shards N
    cols: int  # padded per-shard length c (multiple of LANE)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape or (1,)))


def build_plan(tree: Any, num_shards: int, min_elems: int = MIN_ELEMS):
    """Per-leaf packing plan for `tree` (arrays OR ShapeDtypeStructs).

    Deterministic in (shape, dtype, num_shards) only, so plans built from a
    live tree, from jax.eval_shape, or on a different host always agree —
    the property the checkpoint round-trip and the in-step re-derivation
    both rely on.
    """

    def mk(x) -> LeafPlan:
        shape = tuple(x.shape)
        dtype = np.dtype(x.dtype)
        size = int(np.prod(shape or (1,)))
        if (num_shards > 1 and size >= min_elems
                and np.issubdtype(dtype, np.floating)):
            cols = -(-size // num_shards)          # ceil division
            cols = -(-cols // LANE) * LANE         # round up to lane width
            return LeafPlan(True, shape, dtype, num_shards, cols)
        return LeafPlan(False, shape, dtype, num_shards, 0)

    return jax.tree.map(mk, tree)


def _pack_leaf(x: jnp.ndarray, lp: LeafPlan) -> jnp.ndarray:
    flat = jnp.ravel(x)
    flat = jnp.pad(flat, (0, lp.rows * lp.cols - flat.size))
    return flat.reshape(lp.rows, lp.cols)


def _unpack_leaf(x: jnp.ndarray, lp: LeafPlan) -> jnp.ndarray:
    return x.reshape(-1)[: lp.size].reshape(lp.shape)


def pack(tree: Any, plan: Any) -> Any:
    """Canonical layout → packed (N, c) layout (planned leaves only)."""
    return jax.tree.map(
        lambda x, lp: _pack_leaf(x, lp) if lp.packed else x, tree, plan)


def unpack(tree: Any, plan: Any) -> Any:
    """Packed (N, c) layout → canonical layout (shapes from the plan)."""
    return jax.tree.map(
        lambda x, lp: _unpack_leaf(x, lp) if lp.packed else x, tree, plan)


def packed_shardings(mesh: Mesh, plan: Any) -> Any:
    """NamedSharding tree for a PACKED tree: row-sharded over 'data'."""
    return jax.tree.map(
        lambda lp: NamedSharding(mesh, P(DATA_AXIS, None) if lp.packed
                                 else P()), plan)


def opt_state_template(tx: optax.GradientTransformation, params: Any) -> Any:
    """Canonical (unpacked) opt-state structure as ShapeDtypeStructs.

    Used wherever the packed opt_state's original leaf shapes are needed
    but only params are at hand (checkpoint templates, in-step plan
    re-derivation)."""
    return jax.eval_shape(tx.init, params)


def state_plans(tx: optax.GradientTransformation, params: Any,
                has_ema: bool, num_shards: int) -> dict:
    """Plans for the three shardable TrainState trees.

    The EMA mirrors params (same shapes/dtypes — train/state.py creates it
    as jnp.copy(params)), so its plan equals the params-shaped plan."""
    pplan = build_plan(params, num_shards)
    return {
        "opt_state": build_plan(opt_state_template(tx, params), num_shards),
        "ema_params": pplan if has_ema else None,
    }


def sharded_update(mesh: Mesh, tx: optax.GradientTransformation,
                   grads: Any, params: Any, opt_state: Any,
                   ema_params: Optional[Any], ema_decay: float):
    """One ZeRO update: (replicated grads/params, PACKED opt/ema) →
    (replicated new params, PACKED new opt/ema).

    `tx` must be shard-local-safe (elementwise — make_optimizer(...,
    shard_local=True) swaps the global-norm clip for identity; the caller
    applies the clip on the full gradient before this). `opt_state` /
    `ema_params` are in the packed layout; plans are re-derived here from
    the params avals, which is exact because build_plan is deterministic
    in shapes alone.
    """
    n = mesh.shape[DATA_AXIS]
    pplan = build_plan(params, n)
    oplan = build_plan(opt_state_template(tx, params), n)
    opt_specs = jax.tree.map(
        lambda lp: P(DATA_AXIS, None) if lp.packed else P(), oplan)
    param_specs = jax.tree.map(lambda lp: P(), pplan)
    has_ema = ema_params is not None

    def shard_of(x, lp, idx):
        if not lp.packed:
            return x
        return jax.lax.dynamic_slice_in_dim(
            _pack_leaf(x, lp), idx, 1, axis=0)[0]

    def local_row(x, lp):
        # A packed leaf arrives as this replica's (1, c) row under
        # in_spec P('data', None); drop the row axis for elementwise math.
        return x[0] if lp.packed else x

    def to_row(x, lp):
        return x[None] if lp.packed else x

    def body(g_full, p_full, opt_loc, *maybe_ema):
        idx = jax.lax.axis_index(DATA_AXIS)
        g_sh = jax.tree.map(lambda x, lp: shard_of(x, lp, idx),
                            g_full, pplan)
        p_sh = jax.tree.map(lambda x, lp: shard_of(x, lp, idx),
                            p_full, pplan)
        opt_sh = jax.tree.map(local_row, opt_loc, oplan)
        updates, new_opt = tx.update(g_sh, opt_sh, p_sh)
        new_p_sh = optax.apply_updates(p_sh, updates)

        outs = []
        if has_ema:
            ema_sh = jax.tree.map(local_row, maybe_ema[0], pplan)
            new_ema = jax.tree.map(
                lambda e, p: e * ema_decay + p.astype(e.dtype)
                * (1.0 - ema_decay),
                ema_sh, new_p_sh)
            outs = [jax.tree.map(to_row, new_ema, pplan)]

        def gather(p_new, lp):
            if not lp.packed:
                return p_new
            flat = jax.lax.all_gather(p_new, DATA_AXIS, tiled=True)
            return _unpack_leaf(flat, lp)

        new_p_full = jax.tree.map(gather, new_p_sh, pplan)
        return (new_p_full, jax.tree.map(to_row, new_opt, oplan), *outs)

    from novel_view_synthesis_3d_tpu.parallel.ring_attention import \
        _shard_map

    in_specs = [param_specs, param_specs, opt_specs]
    out_specs = [param_specs, opt_specs]
    args = [grads, params, opt_state]
    if has_ema:
        ema_specs = jax.tree.map(
            lambda lp: P(DATA_AXIS, None) if lp.packed else P(), pplan)
        in_specs.append(ema_specs)
        out_specs.append(ema_specs)
        args.append(ema_params)

    fn = _shard_map(body, mesh, in_specs=tuple(in_specs),
                    out_specs=tuple(out_specs))
    out = fn(*args)
    if has_ema:
        new_params, new_opt, new_ema = out
    else:
        (new_params, new_opt), new_ema = out, None
    return new_params, new_opt, new_ema
