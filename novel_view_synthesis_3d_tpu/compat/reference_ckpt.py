"""Import/export of reference-format X-UNet checkpoints.

The reference saves flax msgpack checkpoints of its pmap-replicated param
tree (`/root/reference/train.py:159-167`, restored at
`sampling.py:104-114`; the README's pretrained model ships in this format).
This module converts that tree to/from this repo's layout so reference
checkpoints — including the published pretrained model — load directly.

The two layouts differ in exactly three ways:

1. **Replication axis.** The reference saves params straight out of pmap,
   so every leaf carries a leading device axis (never unreplicated —
   SURVEY.md §3.5). `strip_replica_axis` removes it.
2. **Conv kernels.** The reference uses 3-D `nn.Conv(kernel=(1,3,3))` over
   (B, F, H, W, C) — kernels shaped (1, 3, 3, Cin, Cout). This repo's
   `FrameConv` runs a 2-D conv over (B·F, H, W, C) — kernels (3, 3, Cin,
   Cout), identical math (models/layers.py). The frame axis is squeezed /
   re-inserted.
3. **Scope names for convs.** A reference `Conv_N` at some scope is this
   repo's `FrameConv_N/Conv_0` at the same scope. Everything else (Dense,
   DenseGeneral, GroupNorm wrappers, FiLM, XUNetBlock/ResnetBlock/AttnBlock
   numbering, pos_emb/ref_pose_emb params) is name-identical because both
   models instantiate submodules in the same order.

Use the `reference` config preset with imported weights: it pins the
behavior quirks the weights were trained under (shared-frame GroupNorm
statistics, no attention out-projection, F=2).
"""

from __future__ import annotations

import re
from typing import Optional

import jax
import numpy as np

_CONV_RE = re.compile(r"^Conv_(\d+)$")
_FRAMECONV_RE = re.compile(r"^FrameConv_(\d+)$")


def strip_replica_axis(tree: dict, n_devices: Optional[int] = None) -> dict:
    """Remove the pmap leading device axis from every leaf, if present.

    The reference never unreplicates before saving, so a checkpoint from an
    N-GPU run has every leaf shaped (N, ...). Detection: all leaves share
    the same leading dimension AND every norm `scale` leaf is 2-D (an
    unreplicated GroupNorm scale is 1-D; conv/Dense biases don't work as
    the witness — DenseGeneral biases are legitimately 2-D). Pass
    `n_devices` to skip detection.
    Replica 0 is taken — NOT an average: the reference also never syncs its
    replicas (SURVEY.md §2.3), so each device axis slot holds an
    independently-trained model; slot 0 is "the" model by convention.
    """
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return tree
    if n_devices is None:
        lead = {leaf.shape[0] if np.ndim(leaf) > 0 else None
                for leaf in leaves}
        if len(lead) != 1 or None in lead:
            return tree
        scales = [leaf for path, leaf in _iter_paths(tree)
                  if path[-1] == "scale"]
        if not scales or any(np.ndim(s) != 2 for s in scales):
            return tree
    return jax.tree.map(lambda leaf: np.asarray(leaf)[0], tree)


def _iter_paths(tree: dict, prefix=()):
    for k, v in tree.items():
        if isinstance(v, dict):
            yield from _iter_paths(v, prefix + (k,))
        else:
            yield prefix + (k,), v


def import_reference_params(ref_params: dict) -> dict:
    """Reference param tree (unreplicated) → this repo's param layout."""

    def convert(scope: dict) -> dict:
        out = {}
        for k, v in scope.items():
            m = _CONV_RE.match(k)
            if m and isinstance(v, dict) and "kernel" in v:
                kernel = np.asarray(v["kernel"])
                if kernel.ndim != 5 or kernel.shape[0] != 1:
                    raise ValueError(
                        f"reference conv {k}: expected (1, kh, kw, cin, "
                        f"cout) kernel, got {kernel.shape}")
                entry = {"kernel": kernel[0]}
                if "bias" in v:
                    entry["bias"] = np.asarray(v["bias"])
                out[f"FrameConv_{m.group(1)}"] = {"Conv_0": entry}
            elif isinstance(v, dict):
                out[k] = convert(v)
            else:
                out[k] = np.asarray(v)
        return out

    return convert(ref_params)


def export_reference_params(params: dict) -> dict:
    """This repo's param layout → reference tree (3-D conv kernels)."""

    def convert(scope: dict) -> dict:
        out = {}
        for k, v in scope.items():
            m = _FRAMECONV_RE.match(k)
            if m and isinstance(v, dict) and set(v) == {"Conv_0"}:
                inner = v["Conv_0"]
                entry = {"kernel": np.asarray(inner["kernel"])[None]}
                if "bias" in inner:
                    entry["bias"] = np.asarray(inner["bias"])
                out[f"Conv_{m.group(1)}"] = entry
            elif isinstance(v, dict):
                out[k] = convert(v)
            else:
                out[k] = np.asarray(v)
        return out

    return convert(params)


def load_reference_checkpoint(path: str) -> dict:
    """Load a reference flax-msgpack checkpoint file → this repo's layout.

    Accepts the raw bytes the reference's `checkpoints.save_checkpoint`
    writes (msgpack of the bare param dict, possibly pmap-replicated,
    possibly wrapped in a {'params': ...} or TrainState-shaped dict).
    """
    from flax import serialization

    with open(path, "rb") as fh:
        tree = serialization.msgpack_restore(fh.read())
    # Unwrap TrainState-shaped saves down to the param dict.
    while isinstance(tree, dict) and "params" in tree and (
            set(tree) <= {"params", "step", "opt_state", "tx", "apply_fn"}):
        tree = tree["params"]
    tree = strip_replica_axis(tree)
    return import_reference_params(tree)


def assert_trees_match(a: dict, b: dict, rtol=0.0, atol=0.0) -> None:
    """Structural + numerical equality check (test/debug helper)."""
    pa = dict(_iter_paths(a))
    pb = dict(_iter_paths(b))
    if set(pa) != set(pb):
        only_a = sorted(set(pa) - set(pb))[:5]
        only_b = sorted(set(pb) - set(pa))[:5]
        raise AssertionError(
            f"param tree mismatch; only in first: {only_a}, "
            f"only in second: {only_b}")
    for path, leaf in pa.items():
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(pb[path]), rtol=rtol, atol=atol,
            err_msg="/".join(path))
