"""Interop with the reference implementation's artifact formats."""

from novel_view_synthesis_3d_tpu.compat.reference_ckpt import (
    export_reference_params,
    import_reference_params,
    load_reference_checkpoint,
    strip_replica_axis,
)

__all__ = [
    "export_reference_params",
    "import_reference_params",
    "load_reference_checkpoint",
    "strip_replica_axis",
]
