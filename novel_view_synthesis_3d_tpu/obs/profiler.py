"""Continuous per-op-group profiling (docs/DESIGN.md "Performance
observatory").

PR 4's ``XProfWindow`` captures ONE jax.profiler window per run and
leaves the trace for a human with TensorBoard. This module closes the
loop: ``ContinuousProfiler`` re-arms bounded windows on a cadence
(``obs.profile.every_steps`` / ``window_steps``, on by default), parses
each captured trace host-side into per-``op_group`` device-time totals,
and lands the result where the rest of the observatory already looks —
a ``profile_window`` row in telemetry.jsonl (via the EventBus, the one
write path) plus ``nvs3d_group_device_time_seconds{group}`` gauges.

Attribution vocabulary: the SAME ordered op-group list the cost map,
numerics observatory, and pipeline staging share
(``models/xunet.op_groups``). Trace events are matched against each
group's module label and param names (the XUNet op loop additionally
tags each op with a ``jax.named_scope("og.<label>")`` so HLO op
metadata carries the group name verbatim); device time no pattern
claims is binned LOUDLY as ``other`` — a big ``other`` bucket is a
finding, not a rounding error. Cross-device collective time gets its
own synthetic ``comm`` group so the roofline can classify comm-bound
groups without guessing.

Overhead contract (tier-1 asserted): arming/parsing happens strictly
host-side between dispatches — no jitted code changes, zero new
recompiles, bitwise-identical training outputs profiler on vs off.
Window-armed steps are excluded from the step-rate gauges (the trainer
checks ``armed_steps_total`` across each log interval), and each
``profile_window`` row carries its own measured ``overhead_s`` so the
amortized cost (overhead per window / cadence × step time) is
measurable from artifacts alone; the acceptance test pins it ≤ 1 %.

Trace-format note: jax.profiler writes a Chrome trace-event JSON
(``*.trace.json.gz``) next to the xplane proto. On TPU the device lanes
carry per-HLO-op slices with the named_scope text in the event name; on
CPU the trace holds only compile passes and ``*Executable::Execute``
host slices — those Execute slices are treated as (unattributable)
device time so a CPU-lane window loudly reports ``other`` rather than
an empty window. Parsing tolerates gzip/plain, torn files, and empty
windows: a window that cannot be parsed emits a row with
``error`` set instead of raising — profiling must never fault the run.

No jax at module load (supervisor constraint); jax.profiler is imported
inside the arm/disarm paths only.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

PROFILE_KIND = "profile_window"
OTHER_GROUP = "other"
COMM_GROUP = "comm"
GROUP_TIME_GAUGE = "nvs3d_group_device_time_seconds"
# Subdirectory of the run folder that holds the rolling window captures
# (distinct from the one-shot XProfWindow's "xprof" dir).
PROFILE_DIR = "profile_cont"
# Consecutive arm/disarm failures before the profiler turns itself off
# for the rest of the run (loudly, via a profile_window error row).
MAX_FAILURES = 3

# Substrings that mark a trace lane (process or thread) as device-side.
_DEVICE_LANE_RE = re.compile(
    r"/device:|TensorCore|TPU|XLA Op|Steps|GPU", re.IGNORECASE)
# Host slices that stand in for device execution on backends whose
# traces carry no device lanes (CPU): the executable dispatch itself.
_EXECUTE_RE = re.compile(r"Executable::Execute|XlaModule:")
# Collective-op names across HLO spellings and jax primitive names.
_COMM_RE = re.compile(
    r"all-reduce|all-gather|all-to-all|reduce-scatter|collective-permute"
    r"|psum|all_gather|ppermute|send|recv", re.IGNORECASE)


def group_patterns(
        groups: Sequence[Tuple[str, Sequence[str]]]) -> List[Tuple[str, List[str]]]:
    """Ordered (label, [substring patterns]) used to claim trace events.

    Per group: the explicit ``og.<label>`` named-scope tag first (exact
    vocabulary match), then the flax module / param names (HLO op
    metadata carries them as ``.../ModuleName_k/...`` path segments).
    First match wins in group order, mirroring group_assignment."""
    out: List[Tuple[str, List[str]]] = []
    for label, names in groups:
        pats = [f"og.{label}"]
        for name in names:
            if name not in pats:
                pats.append(name)
        if label not in pats:
            pats.append(label)
        out.append((label, pats))
    return out


def find_trace_file(log_dir: str) -> Optional[str]:
    """Newest Chrome-trace JSON under a jax.profiler log dir (the
    ``plugins/profile/<ts>/<host>.trace.json.gz`` layout), or None."""
    hits: List[str] = []
    for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
        hits.extend(glob.glob(os.path.join(log_dir, pat), recursive=True))
    if not hits:
        return None
    return max(hits, key=lambda p: (os.path.getmtime(p), p))


def load_chrome_trace(path: str) -> Optional[dict]:
    """Parse a (possibly gzipped) Chrome-trace JSON; None on torn or
    unreadable files — the caller bins the window as an error row."""
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt") as fh:
                return json.load(fh)
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError, EOFError):
        return None


def _self_times(evs: List[dict]) -> List[float]:
    """Self time (dur minus immediate children) per complete event of one
    (pid, tid) lane, in the event's own time unit."""
    order = sorted(range(len(evs)),
                   key=lambda i: (evs[i]["ts"], -evs[i]["dur"]))
    self_dur = [0.0] * len(evs)
    stack: List[Tuple[float, int]] = []  # (end_ts, index)
    for i in order:
        ts = evs[i]["ts"]
        dur = evs[i]["dur"]
        while stack and stack[-1][0] <= ts:
            stack.pop()
        if stack:
            self_dur[stack[-1][1]] -= dur
        self_dur[i] += dur
        stack.append((ts + dur, i))
    return self_dur


def attribute_device_time(doc: Optional[dict],
                          patterns: Sequence[Tuple[str, Sequence[str]]]
                          ) -> dict:
    """Per-group device-time totals from one Chrome-trace document.

    Returns {"groups": {label: seconds}, "comm_s", "other_s", "total_s",
    "events", "device_lanes"}. Device lanes are identified by their
    process/thread metadata names; on lanes-free traces (CPU) the host
    ``*Executable::Execute`` slices substitute, which by construction
    land in ``other`` unless a named scope leaked into the slice name —
    the loud-``other`` contract, not a parse failure."""
    out = {"groups": {label: 0.0 for label, _ in patterns},
           "comm_s": 0.0, "other_s": 0.0, "total_s": 0.0,
           "events": 0, "device_lanes": 0}
    if not doc:
        return out
    events = doc.get("traceEvents") or []
    if not isinstance(events, list):
        return out
    # Lane naming: metadata events carry process/thread display names.
    proc_names: Dict[object, str] = {}
    thread_names: Dict[Tuple[object, object], str] = {}
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != "M":
            continue
        name = (ev.get("args") or {}).get("name", "")
        if ev.get("name") == "process_name":
            proc_names[ev.get("pid")] = str(name)
        elif ev.get("name") == "thread_name":
            thread_names[(ev.get("pid"), ev.get("tid"))] = str(name)

    def lane_is_device(pid, tid) -> bool:
        label = (proc_names.get(pid, "") + " "
                 + thread_names.get((pid, tid), ""))
        return bool(_DEVICE_LANE_RE.search(label))

    lanes: Dict[Tuple[object, object], List[dict]] = {}
    exec_lanes: Dict[Tuple[object, object], List[dict]] = {}
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        try:
            ts = float(ev.get("ts", 0.0))
            dur = float(ev.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        if dur <= 0:
            continue
        key = (ev.get("pid"), ev.get("tid"))
        slim = {"ts": ts, "dur": dur, "name": str(ev.get("name", ""))}
        if lane_is_device(*key):
            lanes.setdefault(key, []).append(slim)
        elif _EXECUTE_RE.search(slim["name"]):
            exec_lanes.setdefault(key, []).append(slim)
    # Prefer real device lanes; fall back to host Execute slices only
    # when the trace has none (CPU backend).
    chosen = lanes or exec_lanes
    out["device_lanes"] = len(lanes)
    for key, evs in chosen.items():
        selfs = _self_times(evs)
        for ev, self_us in zip(evs, selfs):
            if self_us <= 0:
                continue
            s = self_us / 1e6  # Chrome trace ts/dur are microseconds
            out["events"] += 1
            out["total_s"] += s
            name = ev["name"]
            for label, pats in patterns:
                if any(p in name for p in pats):
                    out["groups"][label] += s
                    break
            else:
                if _COMM_RE.search(name):
                    out["comm_s"] += s
                else:
                    out["other_s"] += s
    for k in ("comm_s", "other_s", "total_s"):
        out[k] = round(out[k], 6)
    out["groups"] = {k: round(v, 6) for k, v in out["groups"].items()}
    return out


class ContinuousProfiler:
    """Re-arming jax.profiler windows with per-group attribution.

    ``on_step(step)`` is called once per loop iteration with the current
    step (training) or dispatch (serving) count, exactly like
    ``XProfWindow.on_step`` — sync-free, host-side. A window arms when
    ``step`` hits the cadence and closes ``window`` units later; closing
    stops the trace, attributes it, emits the ``profile_window`` row and
    per-group gauges, and removes nothing (captures stay on disk under
    ``<results>/profile_cont/window_<step>`` for XProf deep dives).

    ``armed_steps_total`` counts loop iterations observed while a window
    was open (including the closing iteration, which pays the parse):
    the trainer compares it across a log interval and skips the
    step-rate gauges for intervals that overlapped a window. Failures
    never propagate; after MAX_FAILURES consecutive ones the profiler
    disables itself and says so in a final error row.

    ``start_cb``/``stop_cb`` are injectable for tests; the defaults bind
    jax.profiler lazily.
    """

    def __init__(self, log_root: str,
                 groups: Sequence[Tuple[str, Sequence[str]]],
                 bus, registry=None, *,
                 every: int = 500, window: int = 2, unit: str = "step",
                 start_cb: Optional[Callable[[str], None]] = None,
                 stop_cb: Optional[Callable[[], None]] = None):
        self.log_root = log_root
        self.patterns = group_patterns(groups)
        self.bus = bus
        self.every = max(1, int(every))
        self.window = max(1, int(window))
        self.unit = unit
        self.active = False
        self.enabled = True
        self.windows: List[dict] = []
        self.armed_steps_total = 0
        self.overhead_s = 0.0  # cumulative host time arming/parsing
        self.failures = 0
        self._start_step = 0
        self._end_step = 0
        self._last_step: Optional[int] = None
        self._window_dir = ""
        self._start_cb = start_cb
        self._stop_cb = stop_cb
        self._gauge = None
        if registry is not None:
            self._gauge = registry.gauge(
                GROUP_TIME_GAUGE,
                "measured device seconds per op group in the latest "
                "profile window (obs.profile; 'other' = unattributed, "
                "'comm' = collectives)")

    # -- profiler backend ---------------------------------------------
    def _start_trace(self, log_dir: str) -> None:
        if self._start_cb is not None:
            self._start_cb(log_dir)
            return
        import jax

        jax.profiler.start_trace(log_dir)

    def _stop_trace(self) -> None:
        if self._stop_cb is not None:
            self._stop_cb()
            return
        import jax

        jax.profiler.stop_trace()

    # -- window lifecycle ---------------------------------------------
    def on_step(self, step: int) -> None:
        """Advance the window state machine; call every loop iteration."""
        if not self.enabled:
            return
        self._last_step = step
        if self.active:
            self.armed_steps_total += 1
            if step >= self._end_step:
                self._close_window(step)
        elif step > 0 and step % self.every == 0:
            self._arm(step)

    def _arm(self, step: int) -> None:
        t0 = time.perf_counter()
        self._window_dir = os.path.join(self.log_root,
                                        f"window_{step:08d}")
        try:
            os.makedirs(self._window_dir, exist_ok=True)
            self._start_trace(self._window_dir)
        except Exception as exc:  # profiling must never fault the run
            self._fail(step, f"start_trace: {exc!r}")
            return
        self.failures = 0
        self.active = True
        self._start_step = step
        self._end_step = step + self.window
        self.armed_steps_total += 1
        self.overhead_s += time.perf_counter() - t0

    def _close_window(self, step: int) -> None:
        t0 = time.perf_counter()
        self.active = False
        try:
            self._stop_trace()
        except Exception as exc:
            self._fail(step, f"stop_trace: {exc!r}")
            return
        row = {"kind": PROFILE_KIND, "unit": self.unit,
               "step_start": self._start_step, "step_end": step,
               "trace_dir": self._window_dir}
        path = find_trace_file(self._window_dir)
        doc = load_chrome_trace(path) if path else None
        attr = attribute_device_time(doc, self.patterns)
        row.update(attr)
        if path is None:
            row["error"] = "no trace file captured"
        elif doc is None:
            row["error"] = "trace file unreadable (torn or truncated)"
        dt = time.perf_counter() - t0
        self.overhead_s += dt
        row["overhead_s"] = round(dt, 6)
        self.windows.append(row)
        if self.bus is not None:
            self.bus.jsonl_row(row)
        if self._gauge is not None:
            for label, secs in attr["groups"].items():
                self._gauge.set(secs, group=label)
            self._gauge.set(attr["other_s"], group=OTHER_GROUP)
            self._gauge.set(attr["comm_s"], group=COMM_GROUP)

    def _fail(self, step: int, detail: str) -> None:
        self.active = False
        self.failures += 1
        row = {"kind": PROFILE_KIND, "unit": self.unit,
               "step_start": self._start_step, "step_end": step,
               "error": detail}
        if self.failures >= MAX_FAILURES:
            self.enabled = False
            row["disabled"] = True
            print(f"obs: continuous profiler disabled after "
                  f"{self.failures} consecutive failures ({detail})",
                  flush=True)
        self.windows.append(row)
        if self.bus is not None:
            self.bus.jsonl_row(row)

    def close(self) -> None:
        """Finalize an open window (run ended mid-capture); idempotent."""
        if self.active:
            self._close_window(self._last_step
                               if self._last_step is not None
                               else self._end_step)

    # -- overhead accounting ------------------------------------------
    def amortized_overhead(self, step_s: float) -> Optional[float]:
        """Measured profiler overhead as a fraction of run time at the
        configured cadence: (host overhead per window) / (every × step
        wall time). None before the first closed window."""
        if not self.windows or step_s <= 0:
            return None
        per_window = self.overhead_s / len(self.windows)
        return per_window / (self.every * step_s)


def make_profiler(pcfg, results_folder: str, model_cfg, bus,
                  registry=None, *, unit: str = "step"
                  ) -> Optional[ContinuousProfiler]:
    """Build the run's ContinuousProfiler from ObsProfileConfig, or None
    when disabled. `unit` picks the training (steps) vs serving
    (dispatches) cadence fields. Imports models.xunet lazily — obs stays
    jax-free at module load."""
    if pcfg is None or not getattr(pcfg, "enabled", False):
        return None
    if unit == "dispatch":
        every = int(getattr(pcfg, "serve_every_dispatches", 0))
        window = int(getattr(pcfg, "serve_window_dispatches", 0))
    else:
        every = int(getattr(pcfg, "every_steps", 0))
        window = int(getattr(pcfg, "window_steps", 0))
    if every <= 0 or window <= 0:
        return None
    from novel_view_synthesis_3d_tpu.models.xunet import op_groups

    return ContinuousProfiler(
        os.path.join(results_folder, PROFILE_DIR),
        op_groups(model_cfg), bus, registry,
        every=every, window=window, unit=unit)


def profile_rows(results_folder: str) -> List[dict]:
    """All profile_window rows a run has landed in telemetry.jsonl,
    in file order; [] when the file or rows are absent. Torn trailing
    lines are skipped (crash-tolerant, same policy as load_ledger)."""
    from novel_view_synthesis_3d_tpu.obs.bus import jsonl_path

    path = jsonl_path(results_folder)
    if not os.path.exists(path):
        return []
    out: List[dict] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if isinstance(row, dict) and row.get("kind") == PROFILE_KIND:
                out.append(row)
    return out
