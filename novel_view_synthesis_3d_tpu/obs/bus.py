"""EventBus: the single write path for run telemetry files.

Before this module, three independent writers appended to the same CSV
formats — the trainer's MetricsLogger (metrics.csv + events.csv), the
supervisor's standalone log_event, and the sampling service's
_log_event — each with its own open/flush policy. They now all route
through here: this module is the ONLY place in the package that names
``events.csv`` / ``metrics.csv`` (a conformance test enforces it), so the
schema and durability policy cannot fork again.

Sinks:

  - ``metrics.csv``: the training curve table. Header comes from the
    producer (MetricsLogger.HEADER); a resumed run with a DIFFERENT
    header rotates the old file aside rather than appending misaligned
    rows (the pre-existing policy, now in one place).
  - ``events.csv``: the fault/serve event log, schema fixed at
    ``step,event,detail`` — byte-compatible with every PR-1/2/3 consumer
    (tools/summarize_bench.py, the watchdog/fault drills).
  - ``telemetry.jsonl``: machine-readable mirror for everything the CSVs
    can't carry — span records, gauge samples, arbitrary rows — one JSON
    object per line (tools/summarize_bench.py's telemetry section reads
    this).

Durability policy (ONE place): every row is flushed to the OS on write
(a crash loses at most the current line); fsync is deliberately not
issued per row — metrics are telemetry, not state, and per-row fsync on
network filesystems has been observed costing more than the train step.

No jax imports here: the supervisor process (train/supervisor.py) writes
events while deliberately holding no JAX state.
"""

from __future__ import annotations

import csv
import json
import os
import threading
import time
from typing import IO, Callable, Optional, Sequence

# model_version (PR 5): which registry version was live when the event
# fired — "" for events outside a versioned-serving context. Consumers
# parse by column NAME (csv.DictReader), so the added column is
# backward-compatible; files written under the old 3-column header are
# rotated aside on first append, same policy as _CsvTable.
EVENTS_HEADER = ("step", "event", "detail", "model_version")
_METRICS_FILE = "metrics.csv"
_EVENTS_FILE = "events.csv"
_JSONL_FILE = "telemetry.jsonl"
_NUMERICS_FILE = "numerics.jsonl"


def metrics_csv_path(results_folder: str) -> str:
    return os.path.join(results_folder, _METRICS_FILE)


def events_csv_path(results_folder: str) -> str:
    return os.path.join(results_folder, _EVENTS_FILE)


def jsonl_path(results_folder: str) -> str:
    return os.path.join(results_folder, _JSONL_FILE)


def numerics_path(results_folder: str) -> str:
    return os.path.join(results_folder, _NUMERICS_FILE)


class _CsvTable:
    """Append-only CSV with header ownership + schema-rotation.

    If the file already exists with a DIFFERENT header (older build), it
    is rotated to ``<path>.old`` instead of appending misaligned rows
    under the stale header."""

    def __init__(self, path: str, header: Sequence[str]):
        self.path = path
        self.header = list(header)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        if os.path.exists(path) and os.path.getsize(path):
            with open(path) as fh:
                old_header = fh.readline().strip().split(",")
            if old_header != self.header:
                os.replace(path, path + ".old")
        self._fh: IO = open(path, "a", newline="")
        self._csv = csv.writer(self._fh)
        if self._fh.tell() == 0:
            self._csv.writerow(self.header)
            self._fh.flush()

    def append(self, row: Sequence) -> None:
        self._csv.writerow(row)
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()


def append_event(results_folder: str, step: int, kind: str,
                 detail: str = "", *, model_version: str = "",
                 echo: Optional[str] = None) -> None:
    """One events.csv row, opened per call (events are rare by
    construction — no handle to leak across the supervisor's child
    generations or the service's lifetime). Schema:
    step,event,detail,model_version.

    `echo`: optional prefix for a human-readable stdout line (e.g.
    "[fault]", "[supervisor]"); None stays silent.
    """
    os.makedirs(results_folder, exist_ok=True)
    path = events_csv_path(results_folder)
    new = not os.path.exists(path) or os.path.getsize(path) == 0
    if not new:
        # A pre-model_version file (3-column header) rotates aside rather
        # than taking misaligned 4-column rows under the stale header.
        with open(path) as fh:
            old_header = fh.readline().strip().split(",")
        if old_header != list(EVENTS_HEADER):
            os.replace(path, path + ".old")
            new = True
    with open(path, "a", newline="") as fh:
        w = csv.writer(fh)
        if new:
            w.writerow(EVENTS_HEADER)
        w.writerow([step, kind, detail, model_version])
        fh.flush()
    if echo is not None:
        print(f"{echo} step {step}: {kind}"
              + (f" ({detail})" if detail else ""), flush=True)


def read_events(results_folder: str) -> list:
    """events.csv rows as dicts keyed by column name (tolerates the
    pre-model_version 3-column schema — missing columns read as "").
    Readers live here with the writer so the schema has one home;
    returns [] when the run never emitted an event."""
    path = events_csv_path(results_folder)
    if not os.path.exists(path):
        return []
    with open(path, newline="") as fh:
        return [dict(row) for row in csv.DictReader(fh)]


class EventBus:
    """Per-run telemetry fan-out over one results folder.

    Thread-safe: the trainer's main loop, the device-monitor thread, and
    the tracer's completion callback all publish concurrently. Sinks are
    lazy — files appear only once something is written to them, so a
    bus constructed for a run that never emits JSONL leaves no empty
    file behind."""

    def __init__(self, results_folder: str, *, jsonl: bool = True,
                 jsonl_max_bytes: int = 0):
        self.results_folder = results_folder
        self._jsonl_enabled = jsonl
        # Size cap: past this many bytes telemetry.jsonl rotates aside
        # to .old (one generation kept — the _CsvTable stale-schema
        # convention) so a multi-day serve run cannot fill the disk.
        # 0 = unbounded.
        self._jsonl_max_bytes = int(jsonl_max_bytes)
        # Pre-serialization tap (the flight recorder): sees EVERY row,
        # including when the JSONL sink is off, and must never fault
        # the producer.
        self.tap: Optional[Callable[[dict], None]] = None
        self._lock = threading.Lock()
        self._metrics: Optional[_CsvTable] = None
        self._jsonl_fh: Optional[IO] = None
        self._numerics_fh: Optional[IO] = None

    # -- metrics.csv ---------------------------------------------------
    def metrics_row(self, header: Sequence[str], row: Sequence) -> None:
        """Append one metrics.csv row; the first call fixes the header
        (rotating any stale-schema file aside)."""
        with self._lock:
            if self._metrics is None:
                self._metrics = _CsvTable(
                    metrics_csv_path(self.results_folder), header)
            self._metrics.append(row)

    # -- events.csv ----------------------------------------------------
    def event(self, step: int, kind: str, detail: str = "", *,
              model_version: str = "",
              echo: Optional[str] = "[fault]") -> None:
        """events.csv row + JSONL mirror + optional stdout echo."""
        append_event(self.results_folder, step, kind, detail,
                     model_version=model_version, echo=echo)
        row = {"kind": "event", "step": step, "event": kind,
               "detail": detail}
        if model_version:
            row["model_version"] = model_version
        self.jsonl_row(row)

    # -- telemetry.jsonl -----------------------------------------------
    def jsonl_row(self, obj: dict) -> None:
        # pid scopes process-local ids (request_id, dispatch ordinals)
        # when a supervised respawn APPENDS to its predecessor's file:
        # reconstruction must never join incarnation A's dispatch rows
        # into incarnation B's request of the same recycled id.
        row = dict(obj, t=round(time.time(), 3), pid=os.getpid())
        if self.tap is not None:
            try:
                self.tap(row)
            except Exception:
                pass  # a forensics sink fault is never the run's fault
        if not self._jsonl_enabled:
            return
        try:
            line = json.dumps(row)
        except (TypeError, ValueError):
            return  # non-serializable telemetry is dropped, never fatal
        with self._lock:
            if self._jsonl_fh is None:
                os.makedirs(self.results_folder, exist_ok=True)
                self._jsonl_fh = open(
                    jsonl_path(self.results_folder), "a")
            self._jsonl_fh.write(line + "\n")
            self._jsonl_fh.flush()
            if (self._jsonl_max_bytes
                    and self._jsonl_fh.tell() >= self._jsonl_max_bytes):
                path = jsonl_path(self.results_folder)
                self._jsonl_fh.close()
                self._jsonl_fh = None
                os.replace(path, path + ".old")

    # -- numerics.jsonl ------------------------------------------------
    def numerics_row(self, obj: dict) -> None:
        """One numerics.jsonl row (per-layer-group stats / spike records,
        obs/numerics.py). Its own sink: the producer opted in via
        train.numerics.enabled, so rows write even when the general JSONL
        sink is off — but the flight-recorder tap still sees every row
        first, same as jsonl_row."""
        row = dict(obj, t=round(time.time(), 3))
        if self.tap is not None:
            try:
                self.tap(row)
            except Exception:
                pass  # a forensics sink fault is never the run's fault
        try:
            line = json.dumps(row)
        except (TypeError, ValueError):
            return  # non-serializable telemetry is dropped, never fatal
        with self._lock:
            if self._numerics_fh is None:
                os.makedirs(self.results_folder, exist_ok=True)
                self._numerics_fh = open(
                    numerics_path(self.results_folder), "a")
            self._numerics_fh.write(line + "\n")
            self._numerics_fh.flush()

    def span_record(self, rec: dict) -> None:
        """JSONL row for one tracer span record: {"kind":"span", name,
        dur_s, ...attrs} — what summarize_bench's percentile section
        reads. Wire as Tracer(on_complete=bus.span_record)."""
        self.jsonl_row({"kind": "span", "name": rec["name"],
                        "dur_s": round(rec["dur"], 6),
                        "thread": rec.get("thread", ""),
                        **{k: v for k, v in rec.get("attrs", {}).items()
                           if isinstance(v, (int, float, str, bool))}})

    def gauge_record(self, name: str, value: float, **labels) -> None:
        self.jsonl_row({"kind": "gauge", "name": name,
                        "value": value, "labels": labels})

    def close(self) -> None:
        """Release the open handles. NOT sticky: a later write reopens
        (append) — a Trainer whose train() ran twice keeps logging."""
        with self._lock:
            if self._metrics is not None:
                self._metrics.close()
                self._metrics = None
            if self._jsonl_fh is not None:
                self._jsonl_fh.close()
                self._jsonl_fh = None
            if self._numerics_fh is not None:
                self._numerics_fh.close()
                self._numerics_fh = None
