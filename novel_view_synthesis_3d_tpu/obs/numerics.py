"""Per-layer-group training numerics (docs/DESIGN.md "Training numerics
& compile observatory").

Two halves, split exactly at the device/host boundary:

  - ``group_stats`` runs INSIDE the jitted train step (train/step.py):
    per-layer-group grad norm, param norm, update/param RMS ratio, grad
    max-abs, and non-finite value counts, grouped by the pipeline op list
    (models/xunet.op_groups — one group per op, so numerics attribution
    and pipeline staging speak the same vocabulary). The reductions are
    READ-ONLY and ALWAYS traced into the step program — the
    ``train.numerics.enabled`` flag gates only the host-side consumer
    below, so enabling stats is bitwise identical with zero recompiles
    by construction (there is exactly one program either way;
    decimation is host-side).
  - ``NumericsMonitor`` runs on the HOST (trainer loop): decimates per
    ``train.numerics.every``, publishes rows to the EventBus's
    numerics.jsonl sink and ``nvs3d_grad_norm{group}`` gauges, and runs
    per-group EWMA spike detection (``numerics_spike`` events with
    z-score + group).

Module-load constraint: no jax imports at the top level — the obs
package must stay importable by the jax-free supervisor process. jax is
imported lazily inside the traced helpers.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

# Stat names emitted per group, in row order. "nonfinite" is an int
# count of non-finite gradient values in the group; the rest are f32.
STAT_KEYS = ("grad_norm", "param_norm", "update_ratio", "grad_max",
             "nonfinite")

# EWMA warmup: a group needs this many accepted samples before the spike
# detector may flag it (an unseeded variance would z-score everything).
MIN_SPIKE_SAMPLES = 5


def group_labels(groups: Sequence[Tuple[str, Sequence[str]]]) -> List[str]:
    return [label for label, _ in groups]


def group_assignment(groups: Sequence[Tuple[str, Sequence[str]]],
                     param_keys: Sequence[str]) -> Dict[str, int]:
    """Map each top-level param-tree key to its group index.

    Raises loudly (at step-build/trace time, not mid-run) if the param
    tree holds a key no group claims — a model change that outgrew the
    op list must fail the build, not silently misattribute stats."""
    assign: Dict[str, int] = {}
    for gi, (label, names) in enumerate(groups):
        for name in names:
            assign[name] = gi
    unknown = sorted(k for k in param_keys if k not in assign)
    if unknown:
        raise ValueError(
            f"numerics: param keys {unknown} not claimed by any layer "
            "group — models/xunet.op_groups is out of sync with the "
            "param tree")
    return assign


def group_stats(assign: Dict[str, int], num_groups: int, *,
                grads, params, new_params) -> dict:
    """Traced per-group reductions; call inside the jitted train step.

    Returns {stat: (G,) array}. `params` is the pre-update tree,
    `new_params` the post-update tree (equal on guard-skipped steps, so
    update_ratio reads 0 there — itself a diagnostic). All three trees
    are replicated at the finish_step boundary in every update-sharding
    mode, so the same reduction text serves replicated/zero/pipeline.
    """
    import jax
    import jax.numpy as jnp

    # One flat f32 vector per group (grads / params / new_params), then
    # ONE reduction per stat per group. Per-leaf reductions compile an
    # HLO instruction per (leaf, stat) pair — hundreds of tiny ops that
    # measurably slow every step build; ravel+concat keeps the program
    # text to ~2 cheap ops per leaf plus a handful per group.
    g_parts: List[list] = [[] for _ in range(num_groups)]
    p_parts: List[list] = [[] for _ in range(num_groups)]
    n_parts: List[list] = [[] for _ in range(num_groups)]
    for key in grads:
        gi = assign[key]
        for g in jax.tree.leaves(grads[key]):
            g_parts[gi].append(g.ravel().astype(jnp.float32))
        for p in jax.tree.leaves(params[key]):
            p_parts[gi].append(p.ravel().astype(jnp.float32))
        for n in jax.tree.leaves(new_params[key]):
            n_parts[gi].append(n.ravel().astype(jnp.float32))

    zf = jnp.zeros((), jnp.float32)
    grad_ss, param_ss, update_ss, grad_max, nonfinite = [], [], [], [], []
    for gi in range(num_groups):
        if not g_parts[gi]:  # op with no live params (e.g. pure reshape)
            grad_ss.append(zf)
            param_ss.append(zf)
            update_ss.append(zf)
            grad_max.append(zf)
            nonfinite.append(jnp.zeros((), jnp.int32))
            continue
        gcat = jnp.concatenate(g_parts[gi])
        pcat = jnp.concatenate(p_parts[gi])
        ncat = jnp.concatenate(n_parts[gi])
        grad_ss.append(jnp.sum(jnp.square(gcat)))
        param_ss.append(jnp.sum(jnp.square(pcat)))
        update_ss.append(jnp.sum(jnp.square(ncat - pcat)))
        grad_max.append(jnp.max(jnp.abs(gcat)))
        # Count of non-finite VALUES (bf16→f32 casts preserve
        # finiteness); >0 iff the group holds any bad gradient, which is
        # all first_bad_group and the anomaly guard consume.
        nonfinite.append(jnp.sum(~jnp.isfinite(gcat)).astype(jnp.int32))
    grad_ss = jnp.stack(grad_ss)
    param_ss = jnp.stack(param_ss)
    update_ss = jnp.stack(update_ss)
    # Same element count divides both RMS terms, so the ratio reduces to
    # sqrt(update_ss)/sqrt(param_ss); epsilon guards empty/zero groups.
    param_norm = jnp.sqrt(param_ss)
    return {
        "grad_norm": jnp.sqrt(grad_ss),
        "param_norm": param_norm,
        "update_ratio": jnp.sqrt(update_ss) / jnp.maximum(param_norm,
                                                          1e-12),
        "grad_max": jnp.stack(grad_max),
        "nonfinite": jnp.stack(nonfinite),
    }


def first_bad_group(labels: Sequence[str], nonfinite_counts) -> str:
    """Host-side: the first (lowest-op-index) group with a non-finite
    gradient leaf — the NaN provenance attached to anomaly events and
    flight dumps. "" when every group is clean."""
    for label, count in zip(labels, nonfinite_counts):
        if int(count) > 0:
            return label
    return ""


class NumericsMonitor:
    """Host-side consumer of the in-jit group stats.

    One per Trainer. `observe(step, stats)` decimates per `every`,
    pulls the tiny (G,)-shaped arrays off device, writes one
    numerics.jsonl row, updates the grad-norm gauges, and runs the
    per-group EWMA spike detector. Returns the decoded row (tests, NaN
    provenance) or None on decimated steps."""

    def __init__(self, labels: Sequence[str], bus, registry=None, *,
                 every: int = 1, spike_z: float = 6.0,
                 ewma_decay: float = 0.9):
        self.labels = list(labels)
        self._bus = bus
        self._every = max(1, int(every))
        self._spike_z = float(spike_z)
        self._decay = float(ewma_decay)
        n = len(self.labels)
        self._mean = [0.0] * n
        self._var = [0.0] * n
        self._samples = [0] * n
        self.rows = 0
        self.spikes: List[dict] = []
        self._gauge = (registry.gauge(
            "nvs3d_grad_norm",
            "Per-layer-group gradient norm (train.numerics)")
            if registry is not None else None)

    def observe(self, step: int, stats: dict) -> Optional[dict]:
        if step % self._every != 0:
            return None
        import numpy as np

        decoded = {}
        for key in STAT_KEYS:
            if key in stats:
                decoded[key] = np.asarray(stats[key]).tolist()
        per_group = {
            label: {k: decoded[k][i] for k in decoded}
            for i, label in enumerate(self.labels)}
        row = {"kind": "numerics", "step": int(step), "groups": per_group}
        self._bus.numerics_row(row)
        self.rows += 1
        for i, label in enumerate(self.labels):
            gn = float(decoded.get("grad_norm", [0.0] * len(self.labels))[i])
            if self._gauge is not None:
                self._gauge.set(gn, group=label)
            self._spike_check(step, i, label, gn)
        return row

    def _spike_check(self, step: int, i: int, label: str,
                     grad_norm: float) -> None:
        """EWMA z-score on the group's grad norm. Non-finite samples are
        never folded into the baseline (they are the anomaly guard's
        department); spiking samples are folded AFTER judging, so a
        slow drift re-baselines while a step spike still flags."""
        if not math.isfinite(grad_norm):
            return
        if self._samples[i] >= MIN_SPIKE_SAMPLES:
            std = math.sqrt(max(self._var[i], 0.0))
            if std > 0.0:
                z = (grad_norm - self._mean[i]) / std
                if z > self._spike_z:
                    spike = {"kind": "numerics_spike", "step": int(step),
                             "group": label, "z": round(z, 2),
                             "grad_norm": grad_norm}
                    self.spikes.append(spike)
                    self._bus.numerics_row(spike)
                    self._bus.event(
                        step, "numerics_spike",
                        f"group={label} z={z:.1f} "
                        f"grad_norm={grad_norm:.3e}",
                        echo="[numerics]")
        d = self._decay
        delta = grad_norm - self._mean[i]
        self._mean[i] += (1.0 - d) * delta
        self._var[i] = d * (self._var[i] + (1.0 - d) * delta * delta)
        self._samples[i] += 1
