"""Roofline attribution: measured group time × analytic cost map
(docs/DESIGN.md "Performance observatory").

The cost map (obs/compiles.xunet_costmap) knows each op group's analytic
FLOPs and bytes; the continuous profiler (obs/profiler) knows its
MEASURED device seconds; devmon knows the chip's peak FLOPs/s and HBM
bytes/s. This module joins the three into per-group roofline rows:

    mfu        = flops / (time × peak_flops)
    bw_util    = bytes / (time × peak_bytes_per_s)
    ideal_s    = max(flops / peak_flops, bytes / peak_bytes_per_s)
    headroom_s = time − ideal_s          (what an optimal kernel saves)
    bound      = comm | compute | memory | unknown

``bound`` is the roofline verdict: compute when MFU dominates bandwidth
utilization, memory when the reverse, comm for the synthetic collective
group, unknown when the chip's peaks aren't tabulated (CPU) or the
group was never measured. The top-k-by-headroom list is the target list
for the ROADMAP perf arcs — it names where an optimization pays before
anyone writes one.

Pure host-side joins over dicts; no jax at module load. Peaks are
optional arguments so tests (and `nvs3d obs roofline` on a machine that
didn't run the job) can supply them explicitly.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from novel_view_synthesis_3d_tpu.obs.profiler import (
    COMM_GROUP,
    OTHER_GROUP,
    profile_rows,
)

BOUND_COMM = "comm"
BOUND_COMPUTE = "compute"
BOUND_MEMORY = "memory"
BOUND_UNKNOWN = "unknown"


def costmap_by_group(costmap_rows: Sequence[dict]) -> Dict[str, dict]:
    """Aggregate costmap rows (one per op) into per-group FLOPs/bytes.
    Groups and ops are 1:1 today, but the join sums defensively."""
    out: Dict[str, dict] = {}
    for row in costmap_rows or []:
        group = row.get("group") or row.get("op") or ""
        if not group:
            continue
        agg = out.setdefault(group, {"flops": 0.0, "bytes": 0.0})
        agg["flops"] += float(row.get("flops") or 0.0)
        agg["bytes"] += float(row.get("bytes") or 0.0)
    return out


def _classify(mfu: Optional[float], bw: Optional[float]) -> str:
    if mfu is None and bw is None:
        return BOUND_UNKNOWN
    if mfu is not None and (bw is None or mfu >= bw):
        return BOUND_COMPUTE
    return BOUND_MEMORY


def roofline_rows(costmap_rows: Sequence[dict],
                  group_seconds: Dict[str, float], *,
                  comm_s: float = 0.0, other_s: float = 0.0,
                  peak_flops: Optional[float] = None,
                  peak_bytes_per_s: Optional[float] = None) -> List[dict]:
    """Join per-group measured seconds with analytic cost; one row per
    group, sorted by measured time (descending, unmeasured last). The
    synthetic ``comm``/``other`` buckets ride along so the rendered
    table always accounts for ALL measured device time."""
    cost = costmap_by_group(costmap_rows)
    labels = list(dict.fromkeys(list(group_seconds) + list(cost)))
    rows: List[dict] = []
    for label in labels:
        t = group_seconds.get(label)
        flops = cost.get(label, {}).get("flops", 0.0)
        byts = cost.get(label, {}).get("bytes", 0.0)
        row: dict = {"group": label, "time_s": t,
                     "flops": flops, "bytes": byts}
        mfu = bw = None
        if t and t > 0:
            if flops and peak_flops:
                mfu = flops / (t * peak_flops)
                row["mfu"] = round(mfu, 4)
            if flops:
                row["achieved_flops_per_s"] = flops / t
            if byts and peak_bytes_per_s:
                bw = byts / (t * peak_bytes_per_s)
                row["bw_util"] = round(bw, 4)
            if byts:
                row["achieved_bytes_per_s"] = byts / t
        ideal = 0.0
        if peak_flops and flops:
            ideal = max(ideal, flops / peak_flops)
        if peak_bytes_per_s and byts:
            ideal = max(ideal, byts / peak_bytes_per_s)
        if ideal > 0:
            row["ideal_s"] = round(ideal, 6)
            if t and t > 0:
                row["headroom_s"] = round(t - ideal, 6)
                row["headroom_x"] = round(t / ideal, 2) if ideal else None
        row["bound"] = _classify(mfu, bw)
        rows.append(row)
    if comm_s:
        rows.append({"group": COMM_GROUP, "time_s": comm_s,
                     "flops": 0.0, "bytes": 0.0, "bound": BOUND_COMM})
    if other_s:
        rows.append({"group": OTHER_GROUP, "time_s": other_s,
                     "flops": 0.0, "bytes": 0.0,
                     "bound": BOUND_UNKNOWN})
    rows.sort(key=lambda r: (-(r.get("time_s") or 0.0), r["group"]))
    return rows


def top_headroom(rows: Sequence[dict], k: int = 3) -> List[dict]:
    """The k groups with the most recoverable seconds — the aim list."""
    cands = [r for r in rows if (r.get("headroom_s") or 0.0) > 0.0]
    cands.sort(key=lambda r: -r["headroom_s"])
    return cands[:k]


def analyze_run(run_dir: str, *, peak_flops: Optional[float] = None,
                peak_bytes_per_s: Optional[float] = None,
                window_index: int = -1) -> dict:
    """Roofline a results folder from its artifacts: latest (or indexed)
    profile_window row + costmap.json. Peaks default to the CURRENT
    process's devices (lazily; None on CPU → bound stays unknown with a
    loud note). Returns {"rows", "top", "notes", "window"}."""
    from novel_view_synthesis_3d_tpu.obs.compiles import load_costmap

    notes: List[str] = []
    cost_rows = load_costmap(run_dir)
    if not cost_rows:
        # bench banks the costmap next to, not inside, the run folder.
        cost_rows = load_costmap(os.path.dirname(run_dir) or ".")
    if not cost_rows:
        notes.append("no costmap.json found — analytic FLOPs/bytes "
                     "unavailable, rows carry measured time only")
    rows_all = profile_rows(run_dir)
    windows = [r for r in rows_all if not r.get("error")]
    window: Optional[dict] = None
    if windows:
        window = windows[window_index]
    else:
        notes.append("no profile_window rows in telemetry.jsonl — "
                     "analytic-only roofline (ideal times, no measured "
                     "time; run with obs.profile.enabled to measure)")
    group_seconds = dict((window or {}).get("groups") or {})
    if peak_flops is None or peak_bytes_per_s is None:
        try:
            from novel_view_synthesis_3d_tpu.obs.devmon import (
                device_peak_bytes_per_s,
                device_peak_flops,
            )

            if peak_flops is None:
                peak_flops = device_peak_flops()
            if peak_bytes_per_s is None:
                peak_bytes_per_s = device_peak_bytes_per_s()
        except Exception:
            pass
    if not peak_flops and not peak_bytes_per_s:
        notes.append("chip peaks unknown (CPU or untabulated kind) — "
                     "bound classification degraded to 'unknown'")
    if window and window.get("other_s", 0.0) > 0.5 * max(
            window.get("total_s") or 1e-12, 1e-12):
        notes.append(
            f"{window['other_s']:.3f}s of {window.get('total_s', 0.0):.3f}s "
            "device time is unattributed ('other') — group tagging did "
            "not reach this trace (CPU lane, or named scopes stripped)")
    rows = roofline_rows(
        cost_rows, group_seconds,
        comm_s=float((window or {}).get("comm_s") or 0.0),
        other_s=float((window or {}).get("other_s") or 0.0),
        peak_flops=peak_flops, peak_bytes_per_s=peak_bytes_per_s)
    return {"rows": rows, "top": top_headroom(rows), "notes": notes,
            "window": window}


def render(report: dict, k: int = 3) -> str:
    """Human table for `nvs3d obs roofline` — fixed-width, stdlib only."""
    lines: List[str] = []
    win = report.get("window")
    if win:
        lines.append(
            f"profile window [{win.get('step_start')}, "
            f"{win.get('step_end')}) unit={win.get('unit', 'step')} "
            f"measured {win.get('total_s', 0.0):.4f}s device time")
    hdr = (f"{'group':<22} {'time_s':>10} {'mfu':>7} {'bw_util':>8} "
           f"{'ideal_s':>10} {'headroom':>9} {'bound':<8}")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for r in report.get("rows", []):
        def fmt(key, spec):
            v = r.get(key)
            return format(v, spec) if isinstance(v, (int, float)) else "-"

        lines.append(
            f"{r['group']:<22} {fmt('time_s', '10.5f')} "
            f"{fmt('mfu', '7.3f')} {fmt('bw_util', '8.3f')} "
            f"{fmt('ideal_s', '10.6f')} {fmt('headroom_s', '9.5f')} "
            f"{r.get('bound', BOUND_UNKNOWN):<8}")
    top = top_headroom(report.get("rows", []), k)
    if top:
        names = ", ".join(
            f"{r['group']} ({r['headroom_s']:.4f}s, {r['bound']})"
            for r in top)
        lines.append(f"top-{len(top)} headroom: {names}")
    for note in report.get("notes", []):
        lines.append(f"note: {note}")
    return "\n".join(lines)
