"""Metrics registry: counters / gauges / histograms with Prometheus text
exposition.

One process-wide registry (``get_registry()``) collects every telemetry
number the run produces — step counters, throughput and utilization
gauges (device memory, MFU, imgs/sec), and per-phase span histograms fed
by ``obs.trace.Tracer``. The registry renders the Prometheus text format
(version 0.0.4) that ``obs.server`` serves at ``/metrics``; no external
client library is involved (stdlib only, nothing to install on a TPU VM).

Metric families follow Prometheus conventions: a family has one name,
help string, and type; children are addressed by label keyword arguments
at the call site (``gauge.set(v, device="0")``). Histograms keep the
cumulative bucket/sum/count triple the exposition format requires PLUS a
bounded sliding window of raw observations so percentile summaries
(`bench.py` snapshots, `tools/summarize_bench.py`) don't need a second
collection path.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Default histogram ladder, in seconds: spans range from sub-ms host work
# to multi-minute compiles; roughly-2.5x spacing keeps the bucket count
# (18) small enough to scrape cheaply while resolving both ends.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
                   600.0)


def _label_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    for k in labels:
        if not _LABEL_RE.match(k):
            raise ValueError(f"invalid Prometheus label name {k!r}")
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(key: Tuple[Tuple[str, str], ...],
                extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    items = list(key) + list(extra)
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in items) + "}"


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class _Family:
    kind = "untyped"

    def __init__(self, name: str, help_: str):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid Prometheus metric name {name!r}")
        self.name = name
        self.help = help_
        self._lock = threading.Lock()
        self._children: Dict[Tuple[Tuple[str, str], ...], object] = {}

    def header_lines(self) -> list:
        return [f"# HELP {self.name} {_escape(self.help)}",
                f"# TYPE {self.name} {self.kind}"]


class Counter(_Family):
    kind = "counter"

    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = _label_key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + n

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._children.get(_label_key(labels), 0.0))

    def render(self) -> list:
        with self._lock:
            children = dict(self._children) or {(): 0.0}
        return [f"{self.name}{_fmt_labels(k)} {_fmt_value(v)}"
                for k, v in sorted(children.items())]


class Gauge(_Family):
    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._children[_label_key(labels)] = float(v)

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            got = self._children.get(_label_key(labels))
        return None if got is None else float(got)

    def max_value(self) -> Optional[float]:
        """Largest child value (e.g. peak HBM across devices)."""
        with self._lock:
            return max(self._children.values(), default=None)

    def render(self) -> list:
        with self._lock:
            children = dict(self._children)
        return [f"{self.name}{_fmt_labels(k)} {_fmt_value(v)}"
                for k, v in sorted(children.items())]


class _HistChild:
    __slots__ = ("bucket_counts", "total", "count", "window")

    def __init__(self, n_buckets: int, window: int):
        import collections

        self.bucket_counts = [0] * n_buckets
        self.total = 0.0
        self.count = 0
        self.window = collections.deque(maxlen=window)


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name: str, help_: str,
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 window: int = 4096):
        super().__init__(name, help_)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        self._window = max(1, window)

    def observe(self, v: float, **labels) -> None:
        v = float(v)
        key = _label_key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = _HistChild(
                    len(self.buckets), self._window)
            for i, bound in enumerate(self.buckets):
                if v <= bound:
                    child.bucket_counts[i] += 1
                    break
            child.total += v
            child.count += 1
            child.window.append(v)

    def percentiles(self, **labels) -> dict:
        """{count, mean_s, p50_s, p90_s, p99_s} over the sliding window
        (count is total-ever, matching ServiceStats semantics)."""
        import numpy as np

        with self._lock:
            child = self._children.get(_label_key(labels))
            vals = list(child.window) if child else []
            count = child.count if child else 0
        if not vals:
            return {}
        arr = np.asarray(vals)
        return {
            "count": count,
            "mean_s": float(arr.mean()),
            "p50_s": float(np.percentile(arr, 50)),
            "p90_s": float(np.percentile(arr, 90)),
            "p99_s": float(np.percentile(arr, 99)),
        }

    def label_sets(self) -> list:
        with self._lock:
            return [dict(k) for k in self._children]

    def render(self) -> list:
        with self._lock:
            children = {k: (list(c.bucket_counts), c.total, c.count)
                        for k, c in self._children.items()}
        lines = []
        for key, (counts, total, count) in sorted(children.items()):
            cum = 0
            for bound, n in zip(self.buckets, counts):
                cum += n
                lines.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels(key, (('le', _fmt_value(bound)),))}"
                    f" {cum}")
            lines.append(
                f"{self.name}_bucket{_fmt_labels(key, (('le', '+Inf'),))}"
                f" {count}")
            lines.append(f"{self.name}_sum{_fmt_labels(key)} "
                         f"{_fmt_value(total)}")
            lines.append(f"{self.name}_count{_fmt_labels(key)} {count}")
        return lines


class MetricsRegistry:
    """Name-keyed family registry; family constructors are idempotent
    (same name + same kind returns the existing family, so independent
    modules can declare the metrics they touch without coordination)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _get(self, cls, name: str, help_: str, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}, not {cls.kind}")
                return fam
            fam = self._families[name] = cls(name, help_, **kw)
            return fam

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(Counter, name, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(Gauge, name, help_)

    def histogram(self, name: str, help_: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  window: int = 4096) -> Histogram:
        return self._get(Histogram, name, help_, buckets=buckets,
                         window=window)

    def families(self) -> Iterable[_Family]:
        with self._lock:
            return list(self._families.values())

    def render_prometheus(self) -> str:
        lines = []
        for fam in sorted(self.families(), key=lambda f: f.name):
            lines.extend(fam.header_lines())
            lines.extend(fam.render())
        return "\n".join(lines) + "\n"


# -- process-wide default registry ------------------------------------
_default_lock = threading.Lock()
_default_registry: Optional[MetricsRegistry] = None


def get_registry() -> MetricsRegistry:
    """The process's shared registry: the /metrics endpoint scrapes what
    every component (trainer, service, device monitor) writes here."""
    global _default_registry
    with _default_lock:
        if _default_registry is None:
            _default_registry = MetricsRegistry()
        return _default_registry


def reset_registry() -> MetricsRegistry:
    """Fresh default registry (tests: isolate counter state per case)."""
    global _default_registry
    with _default_lock:
        _default_registry = MetricsRegistry()
        return _default_registry
