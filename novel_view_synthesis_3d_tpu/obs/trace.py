"""Hierarchical span tracing with Chrome-trace/Perfetto export.

One `Tracer` instance records one run's spans: the trainer's loop phases
(data_fetch → h2d → train_step → d2h → checkpoint_save / eval), the
serving pipeline (queue_wait → batch_form → compile → device → respond),
and anything else that wraps itself in `tracer.span(...)`. Spans nest via
a thread-local stack, are thread-safe across producer threads (the device
prefetcher, the serving worker), and are BOUNDED — a million-step run
keeps the most recent `max_events` spans and counts the rest as dropped
instead of growing host memory.

The export is Chrome trace-event JSON (`trace.json`), loadable directly
in Perfetto (ui.perfetto.dev) or chrome://tracing: complete events
(`ph: "X"`) on one timeline row per thread, with run_id / host_id /
process_index attribution in the file metadata and per-span args. Span
durations also stream to attached sinks as they complete — the metrics
registry's per-phase histogram (`nvs3d_span_seconds{phase=...}`) and the
EventBus JSONL sink — so the /metrics endpoint and telemetry.jsonl see
exactly the spans the trace file does.

`XProfWindow` arms an on-demand `jax.profiler` trace over a configured
step range (`obs.xprof_steps`): span timestamps and the XProf capture
then cover the same steps, so "where did step time go" can be answered
at both the phase level (this module) and the HLO level (XProf).
"""

from __future__ import annotations

import contextlib
import collections
import json
import os
import socket
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple


def _process_index() -> int:
    """jax.process_index() without importing jax at module load (the
    supervisor process deliberately holds no JAX state)."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


def default_run_id() -> str:
    """Sortable, collision-resistant id for one run of one process."""
    return f"{time.strftime('%Y%m%d-%H%M%S')}-p{os.getpid()}"


class Span:
    """Handle yielded by `Tracer.span`; `set(**attrs)` attaches attributes
    that are only known inside the block (e.g. the step count a dispatch
    advanced to)."""

    __slots__ = ("name", "attrs", "t0")

    def __init__(self, name: str, attrs: dict, t0: float):
        self.name = name
        self.attrs = attrs
        self.t0 = t0

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self


class Tracer:
    """Thread-safe bounded span recorder with Chrome-trace export."""

    def __init__(self, *, enabled: bool = True, max_events: int = 200_000,
                 run_id: Optional[str] = None,
                 registry=None, histogram: str = "nvs3d_span_seconds",
                 on_complete: Optional[Callable[[dict], None]] = None):
        self.enabled = enabled
        self.run_id = run_id or default_run_id()
        self.host_id = socket.gethostname()
        self.process_index = _process_index()
        self._lock = threading.Lock()
        self._events: "collections.deque" = collections.deque(
            maxlen=max(1, max_events))
        self.dropped = 0
        self._local = threading.local()  # per-thread open-span stack
        # Wall-clock anchor: spans are timed on the monotonic perf counter
        # (immune to NTP steps); the anchor maps them back to wall time for
        # cross-host alignment and the JSONL sink.
        self._mono0 = time.perf_counter()
        self._wall0 = time.time()
        self._on_complete = on_complete
        self._hist = None
        if registry is not None:
            self._hist = registry.histogram(
                histogram, "span duration per phase (seconds)")

    # -- recording -----------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def now(self) -> float:
        return time.perf_counter()

    def wall(self, mono: float) -> float:
        return self._wall0 + (mono - self._mono0)

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        """Time a block as one span; nests under any enclosing span on the
        same thread. Cheap enough to leave on in production (one perf
        counter read + deque append per side)."""
        if not self.enabled:
            yield Span(name, attrs, 0.0)
            return
        sp = Span(name, attrs, time.perf_counter())
        stack = self._stack()
        stack.append(sp)
        try:
            yield sp
        finally:
            stack.pop()
            self._record(sp.name, sp.t0, time.perf_counter() - sp.t0,
                         sp.attrs, depth=len(stack))

    def add_span(self, name: str, dur_s: float, *,
                 end: Optional[float] = None, **attrs) -> None:
        """Record a span retrospectively from a measured duration (e.g.
        a request's queue wait, known only at dispatch time). `end` is a
        `tracer.now()` stamp; defaults to the present."""
        if not self.enabled:
            return
        end = self.now() if end is None else end
        self._record(name, end - dur_s, dur_s, attrs, depth=0)

    def _record(self, name: str, t0: float, dur: float, attrs: dict,
                depth: int) -> None:
        rec = {
            "name": name,
            "ts": t0,
            "dur": max(0.0, dur),
            "tid": threading.get_ident(),
            "thread": threading.current_thread().name,
            "depth": depth,
            "attrs": attrs,
        }
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(rec)
        if self._hist is not None:
            self._hist.observe(rec["dur"], phase=name)
        if self._on_complete is not None:
            try:
                self._on_complete(rec)
            except Exception:
                pass  # a sink fault must never become the run's fault

    # -- summaries -----------------------------------------------------
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def summary(self) -> Dict[str, dict]:
        """Per-phase {count, mean_s, p50_s, p90_s, p99_s} over the
        retained window — the bench's embedded telemetry snapshot."""
        import numpy as np

        by_name: Dict[str, list] = {}
        for rec in self.events():
            by_name.setdefault(rec["name"], []).append(rec["dur"])
        out = {}
        for name, durs in sorted(by_name.items()):
            arr = np.asarray(durs)
            out[name] = {
                "count": int(arr.size),
                "mean_s": float(arr.mean()),
                "p50_s": float(np.percentile(arr, 50)),
                "p90_s": float(np.percentile(arr, 90)),
                "p99_s": float(np.percentile(arr, 99)),
            }
        return out

    # -- export --------------------------------------------------------
    def export_chrome_trace(self, path: str) -> str:
        """Write the retained spans as Chrome trace-event JSON (Perfetto/
        chrome://tracing loadable). Timestamps are microseconds from the
        tracer's start; `otherData` carries the run/host attribution and
        the wall-clock anchor for cross-run alignment."""
        events = self.events()
        pid = self.process_index
        trace_events: List[dict] = [{
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"nvs3d[{self.run_id}]"},
        }]
        named_threads = set()
        for rec in events:
            if rec["tid"] not in named_threads:
                named_threads.add(rec["tid"])
                trace_events.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": rec["tid"], "args": {"name": rec["thread"]}})
            args = {k: v for k, v in rec["attrs"].items()}
            trace_events.append({
                "ph": "X", "name": rec["name"], "pid": pid,
                "tid": rec["tid"],
                "ts": (rec["ts"] - self._mono0) * 1e6,
                "dur": rec["dur"] * 1e6,
                "args": args,
            })
        doc = {
            "traceEvents": trace_events,
            "displayTimeUnit": "ms",
            "otherData": {
                "run_id": self.run_id,
                "host_id": self.host_id,
                "process_index": pid,
                "wall_time_origin_unix_s": self.wall(self._mono0),
                "dropped_spans": self.dropped,
            },
        }
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as fh:
            json.dump(doc, fh)
        return path


class NullTracer:
    """Disabled tracer with the same surface (obs.enabled=False keeps call
    sites free of None checks)."""

    enabled = False
    dropped = 0
    run_id = ""

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        yield Span(name, attrs, 0.0)

    def add_span(self, name: str, dur_s: float, **kw) -> None:
        pass

    def now(self) -> float:
        return time.perf_counter()

    def events(self) -> List[dict]:
        return []

    def summary(self) -> Dict[str, dict]:
        return {}

    def export_chrome_trace(self, path: str) -> str:
        return path


class XProfWindow:
    """On-demand jax.profiler window over a step range.

    `on_step(step)` is called at each loop iteration with the CURRENT
    step count; the window opens when the step enters [start, end) and
    closes at the first step past it — range checks (not equality) so
    resumed runs that land inside or beyond the window behave sanely.
    The capture lands in `log_dir` (TensorBoard/XProf readable) and its
    wall-clock lines up with the tracer's span timestamps.
    """

    def __init__(self, log_dir: str, steps: Tuple[int, int]):
        self.log_dir = log_dir
        self.start, self.end = int(steps[0]), int(steps[1])
        self.active = False

    @property
    def enabled(self) -> bool:
        return self.end > self.start

    def on_step(self, step: int) -> None:
        if not self.enabled:
            return
        import jax

        if self.active and step >= self.end:
            jax.profiler.stop_trace()
            self.active = False
        elif not self.active and self.start <= step < self.end:
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
            self.active = True

    def close(self) -> None:
        if self.active:
            import jax

            jax.profiler.stop_trace()
            self.active = False
