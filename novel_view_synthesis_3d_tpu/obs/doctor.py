"""The regression doctor: every banked artifact, one ranked diagnosis
(docs/DESIGN.md "Performance observatory").

PRs 14–15 built the evidence — span percentiles, the compile ledger,
the per-op cost map, numerics spikes, bench banks — but reading them
together was still a human job (BENCH_r09's 0.973× sat unnamed until
someone eyeballed the archive). The doctor joins all of it:

  - ``diagnose_pair(run_a, run_b)``: two results folders → span p50
    drifts, recompiles, numerics spikes, cost-map drift, per-group
    device-time drift from profile windows (time up while FLOPs flat →
    named a memory-bound regression), input-pipeline overlap drift.
  - ``diagnose_trajectory(root)``: the banked BENCH_r*/MULTICHIP_r*
    archive (via obs/runindex) → every regressed round named with its
    number and ratio, recovery arcs, span/cost drift of the newest
    round against its own history, infra-gap accounting.
  - ``attribute_fresh(prior, newest)``: the sentry-trip path —
    tools/bench_sentry feeds the round it just judged and embeds the
    top findings in the rc=4 page (replacing its one-line ad-hoc
    ``attribute_regression``).

Findings are dicts {severity: page|warn|info, kind, title, detail,
rank} ranked page-first then by magnitude; ``write_doctor`` lands the
whole diagnosis as ``doctor.json`` in the run folder (this module is
the ONLY place that names that file — conformance-tested, same
single-writer rule as the bus) and ``render`` prints the table the CLI
and sentry show. Ranking heuristic, deliberately simple: severity is
decided by contract (a regressed newest round or a recompile pages; a
drifted-but-healthy signal warns; context is info) and ties break on
the magnitude of the drift — the doctor orders evidence, it does not
hide any.

Stdlib only, no jax: the doctor must run on a machine that never ran
the job, against artifacts alone.
"""

from __future__ import annotations

import json
import os
import statistics
from typing import Dict, List, Optional, Sequence, Tuple

from novel_view_synthesis_3d_tpu.obs.runindex import RunIndex

DOCTOR_FILE = "doctor.json"
SEVERITIES = ("page", "warn", "info")
_SEV_ORDER = {s: i for i, s in enumerate(SEVERITIES)}
# Relative drift (percent) below which a span/group delta is noise.
SPAN_DRIFT_PCT = 5.0
COST_DRIFT_PCT = 0.5
DEFAULT_TOLERANCE_PCT = 2.0


def finding(severity: str, kind: str, title: str, detail: str = "",
            rank: float = 0.0, **evidence) -> dict:
    assert severity in SEVERITIES, severity
    out = {"severity": severity, "kind": kind, "title": title,
           "rank": round(float(rank), 3)}
    if detail:
        out["detail"] = detail
    if evidence:
        out["evidence"] = evidence
    return out


def rank_findings(findings: Sequence[dict]) -> List[dict]:
    return sorted(findings,
                  key=lambda f: (_SEV_ORDER.get(f.get("severity"), 9),
                                 -abs(f.get("rank", 0.0))))


# -- artifact readers (run-folder granularity) ------------------------

def _span_p50s(run_dir: str) -> Dict[str, float]:
    """Per-span p50 seconds from a run's telemetry.jsonl span rows."""
    from novel_view_synthesis_3d_tpu.obs.bus import jsonl_path

    path = jsonl_path(run_dir)
    durs: Dict[str, List[float]] = {}
    if not os.path.exists(path):
        return {}
    with open(path) as fh:
        for line in fh:
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if (isinstance(row, dict) and row.get("kind") == "span"
                    and isinstance(row.get("dur_s"), (int, float))):
                durs.setdefault(str(row.get("name")), []).append(
                    float(row["dur_s"]))
    return {name: statistics.median(v) for name, v in durs.items() if v}


def _span_sums(run_dir: str) -> Dict[str, float]:
    from novel_view_synthesis_3d_tpu.obs.bus import jsonl_path

    path = jsonl_path(run_dir)
    sums: Dict[str, float] = {}
    if not os.path.exists(path):
        return {}
    with open(path) as fh:
        for line in fh:
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if (isinstance(row, dict) and row.get("kind") == "span"
                    and isinstance(row.get("dur_s"), (int, float))):
                name = str(row.get("name"))
                sums[name] = sums.get(name, 0.0) + float(row["dur_s"])
    return sums


def _overlap(run_dir: str) -> Optional[float]:
    """Input-pipeline overlap = 1 − Σdata_fetch/Σtrain_step, the
    summarize_bench definition — one number per run."""
    sums = _span_sums(run_dir)
    step = sums.get("train_step")
    fetch = sums.get("data_fetch")
    if not step or fetch is None:
        return None
    return max(0.0, min(1.0, 1.0 - fetch / step))


def _latest_window(run_dir: str) -> Optional[dict]:
    from novel_view_synthesis_3d_tpu.obs.profiler import profile_rows

    ok = [r for r in profile_rows(run_dir) if not r.get("error")]
    return ok[-1] if ok else None


def _costmap_flops(run_dir: str) -> Dict[str, float]:
    from novel_view_synthesis_3d_tpu.obs.compiles import load_costmap

    rows = load_costmap(run_dir)
    return {str(r.get("group")): float(r.get("flops") or 0.0)
            for r in rows if r.get("group")}


def _drift_pct(old: float, new: float) -> Optional[float]:
    if not old:
        return None
    return (new - old) / old * 100.0


# -- pairwise diagnosis ----------------------------------------------

def diagnose_pair(run_a: str, run_b: str, *,
                  span_drift_pct: float = SPAN_DRIFT_PCT) -> dict:
    """Compare two results folders (A = before, B = after)."""
    findings: List[dict] = []
    spans_a, spans_b = _span_p50s(run_a), _span_p50s(run_b)
    for name in sorted(set(spans_a) & set(spans_b)):
        drift = _drift_pct(spans_a[name], spans_b[name])
        if drift is None:
            continue
        title = (f"span '{name}' p50 {spans_a[name] * 1e3:.1f}ms → "
                 f"{spans_b[name] * 1e3:.1f}ms ({drift:+.1f}%)")
        if abs(drift) >= span_drift_pct:
            findings.append(finding(
                "warn" if drift > 0 else "info", "span_drift", title,
                rank=drift, span=name, drift_pct=round(drift, 1)))
        else:
            findings.append(finding("info", "span_drift", title,
                                    rank=drift))
    from novel_view_synthesis_3d_tpu.obs.compiles import (
        last_recompile,
        load_ledger,
    )

    recompiles = [e for e in load_ledger(run_b)
                  if e.get("kind") == "recompile"]
    if recompiles:
        culprit = last_recompile(run_b) or {}
        findings.append(finding(
            "page", "recompile",
            f"{len(recompiles)} recompile(s) in run B",
            detail=f"changed: {culprit.get('changed', '?')} "
                   f"(name {culprit.get('name', '?')})",
            rank=len(recompiles)))
    else:
        findings.append(finding("info", "recompile",
                                "0 recompiles in run B"))
    from novel_view_synthesis_3d_tpu.obs.bus import read_events

    spikes = [e for e in read_events(run_b)
              if e.get("event") == "numerics_spike"]
    if spikes:
        last = spikes[-1].get("detail", "")
        findings.append(finding(
            "warn", "numerics", f"{len(spikes)} numerics spike(s) in "
            f"run B", detail=last, rank=len(spikes)))
    cm_a, cm_b = _costmap_flops(run_a), _costmap_flops(run_b)
    worst_cm: Optional[Tuple[str, float]] = None
    for group in set(cm_a) & set(cm_b):
        drift = _drift_pct(cm_a[group], cm_b[group])
        if drift is None:
            continue
        if worst_cm is None or abs(drift) > abs(worst_cm[1]):
            worst_cm = (group, drift)
    if worst_cm is not None and abs(worst_cm[1]) >= COST_DRIFT_PCT:
        findings.append(finding(
            "warn", "costmap_drift",
            f"costmap: group '{worst_cm[0]}' flops "
            f"{worst_cm[1]:+.1f}%", rank=worst_cm[1]))
    win_a, win_b = _latest_window(run_a), _latest_window(run_b)
    if win_a and win_b:
        ga, gb = win_a.get("groups") or {}, win_b.get("groups") or {}
        for group in sorted(set(ga) & set(gb)):
            drift = _drift_pct(ga[group], gb[group])
            if drift is None or abs(drift) < span_drift_pct:
                continue
            flops_drift = _drift_pct(cm_a.get(group, 0.0),
                                     cm_b.get(group, 0.0))
            flat = flops_drift is not None and abs(flops_drift) < 1.0
            title = (f"group '{group}' device time {drift:+.1f}%"
                     + (" while flops flat → memory-bound regression"
                        if flat and drift > 0 else ""))
            findings.append(finding(
                "warn" if drift > 0 else "info", "group_time_drift",
                title, rank=drift, group=group,
                drift_pct=round(drift, 1)))
    ov_a, ov_b = _overlap(run_a), _overlap(run_b)
    if ov_a is not None and ov_b is not None:
        title = f"input-pipeline overlap {ov_a:.2f} → {ov_b:.2f}"
        findings.append(finding(
            "warn" if ov_b < ov_a - 0.01 else "info",
            "pipeline_overlap", title, rank=(ov_a - ov_b) * 100))
    return {"mode": "pair", "run_a": run_a, "run_b": run_b,
            "findings": rank_findings(findings)}


# -- trajectory diagnosis --------------------------------------------

def _judge_points(docs: Sequence[Tuple[int, Optional[dict]]],
                  tolerance_pct: float) -> List[dict]:
    """bench_sentry's judging rules over (round, doc) pairs: judgeable
    iff rc==0 with numeric parsed.vs_baseline; regressed when below 1.0
    absolute or below the prior rolling median − tolerance."""
    points: List[dict] = []
    prior: List[float] = []
    for rnd, doc in docs:
        doc = doc or {}
        parsed = doc.get("parsed") or {}
        vs = parsed.get("vs_baseline")
        if doc.get("rc") != 0 or not isinstance(vs, (int, float)):
            points.append({"round": rnd, "judged": False,
                           "rc": doc.get("rc")})
            continue
        vs = float(vs)
        floor = (statistics.median(prior)
                 * (1.0 - tolerance_pct / 100.0)) if prior else None
        regressed = vs < 1.0 or (floor is not None and vs < floor)
        points.append({"round": rnd, "judged": True, "vs_baseline": vs,
                       "regressed": regressed,
                       "lane": parsed.get("lane")
                       or parsed.get("platform")})
        prior.append(vs)
    return points


def diagnose_trajectory(root: str = ".", *,
                        tolerance_pct: float = DEFAULT_TOLERANCE_PCT
                        ) -> dict:
    """Diagnose the banked archive at `root` from artifacts alone."""
    index = RunIndex(root)
    bench_entries = index.rounds("BENCH")
    docs = [(e["round"], index.load_doc(e)) for e in bench_entries]
    points = _judge_points(docs, tolerance_pct)
    findings: List[dict] = []
    judged = [p for p in points if p["judged"]]
    for p in judged:
        if not p["regressed"]:
            continue
        sev = "page" if p is judged[-1] else "warn"
        findings.append(finding(
            sev, "bench_regression",
            f"r{p['round']:02d} regressed: vs_baseline "
            f"{p['vs_baseline']:.3f}×",
            detail=(f"lane {p.get('lane') or '?'}; below its own "
                    "baseline" if p["vs_baseline"] < 1.0
                    else f"below rolling median − {tolerance_pct:g}%"),
            rank=100.0 * (1.0 - p["vs_baseline"]),
            round=p["round"], vs_baseline=p["vs_baseline"]))
    # Recovery arc: from the first judged round AFTER the last
    # regression to the newest — named iff the trajectory actually rose.
    reg_idx = [i for i, p in enumerate(judged) if p["regressed"]]
    if reg_idx and reg_idx[-1] + 1 < len(judged):
        seg = judged[reg_idx[-1] + 1:]
        first, last = seg[0], seg[-1]
        if len(seg) >= 2 and last["vs_baseline"] > first["vs_baseline"]:
            findings.append(finding(
                "info", "recovery",
                f"recovery r{first['round']:02d}→r{last['round']:02d}: "
                f"vs_baseline {first['vs_baseline']:.3f}→"
                f"{last['vs_baseline']:.3f}",
                rank=last["vs_baseline"] - first["vs_baseline"]))
    if judged:
        newest = judged[-1]
        findings.append(finding(
            "info", "newest",
            f"newest judged round r{newest['round']:02d}: "
            f"{newest['vs_baseline']:.3f}× "
            + ("(REGRESSED)" if newest["regressed"] else "(healthy)")))
    unjudged = [p for p in points if not p["judged"]]
    if unjudged:
        rcs = sorted({str(p.get("rc")) for p in unjudged})
        findings.append(finding(
            "info", "infra_gap",
            f"{len(unjudged)} round(s) unjudgeable "
            f"(rc={','.join(rcs)} — infra, no measurement)"))
    # Newest round's embedded telemetry vs its own judged history.
    judged_docs = [doc.get("parsed") or {} for rnd, doc in docs
                   if (doc or {}).get("rc") == 0
                   and isinstance(((doc or {}).get("parsed") or {})
                                  .get("vs_baseline"), (int, float))]
    if len(judged_docs) >= 2:
        findings.extend(_history_drift(judged_docs[:-1],
                                       judged_docs[-1],
                                       since_round=judged[-2]["round"]
                                       if len(judged) >= 2 else None))
    mc_entries = index.rounds("MULTICHIP")
    mc_ok = [e for e in mc_entries if e.get("rc") == 0 and e.get("ok")]
    if mc_entries:
        findings.append(finding(
            "info", "multichip",
            f"multichip: {len(mc_ok)}/{len(mc_entries)} rounds ok"))
    return {"mode": "trajectory", "root": root,
            "tolerance_pct": tolerance_pct, "points": points,
            "findings": rank_findings(findings)}


def _history_drift(prior_parsed: Sequence[dict], newest: dict, *,
                   since_round: Optional[int] = None) -> List[dict]:
    """Span / costmap / profile-group drift of one parsed bench record
    against its judged predecessors (the embedded-telemetry join)."""
    findings: List[dict] = []
    since = f" since r{since_round:02d}" if since_round else ""
    spans_new = (newest.get("telemetry") or {}).get("spans") or {}
    for name, s in sorted(spans_new.items()):
        p50 = s.get("p50_s")
        if not isinstance(p50, (int, float)) or p50 <= 0:
            continue
        prior = [((d.get("telemetry") or {}).get("spans") or {})
                 .get(name, {}).get("p50_s") for d in prior_parsed]
        prior = [p for p in prior
                 if isinstance(p, (int, float)) and p > 0]
        if not prior:
            continue
        base = statistics.median(prior)
        drift = _drift_pct(base, p50)
        if drift is None or abs(drift) < SPAN_DRIFT_PCT:
            continue
        findings.append(finding(
            "warn" if drift > 0 else "info", "span_drift",
            f"span '{name}' p50 {drift:+.1f}%{since} "
            f"({base * 1e3:.1f}ms → {p50 * 1e3:.1f}ms)",
            rank=drift, span=name))
    cm_new = {r.get("group"): r.get("flops")
              for r in (newest.get("costmap") or [])
              if isinstance(r.get("flops"), (int, float))}
    cm_old: Dict[str, float] = {}
    for d in reversed(list(prior_parsed)):
        cm_old = {r.get("group"): r.get("flops")
                  for r in (d.get("costmap") or [])
                  if isinstance(r.get("flops"), (int, float))}
        if cm_old:
            break
    worst: Optional[Tuple[str, float]] = None
    for group, flops in cm_new.items():
        drift = _drift_pct(cm_old.get(group, 0.0), flops)
        if drift is None:
            continue
        if worst is None or abs(drift) > abs(worst[1]):
            worst = (group, drift)
    if worst is not None and abs(worst[1]) >= COST_DRIFT_PCT:
        findings.append(finding(
            "warn", "costmap_drift",
            f"costmap: group '{worst[0]}' flops {worst[1]:+.1f}% vs "
            "last mapped round", rank=worst[1]))
    return findings


# -- sentry-trip attribution -----------------------------------------

def attribute_fresh(prior_parsed: Sequence[dict],
                    newest_parsed: Optional[dict]) -> dict:
    """The bench_sentry rc=4 path: diagnose the round it just judged.
    Returns {"summary": one-liner, "findings": ranked list} — the
    sentry prints the summary in its page and embeds the findings in
    its JSON verdict."""
    if not newest_parsed:
        return {"summary": None, "findings": []}
    findings = _history_drift(prior_parsed, newest_parsed)
    findings = rank_findings(findings)
    if not findings:
        return {"summary": ("no span/costmap telemetry in the compared "
                            "rounds — re-run with telemetry-era "
                            "bench.py for attribution"),
                "findings": []}
    summary = "; ".join(f["title"] for f in findings[:2])
    return {"summary": summary, "findings": findings}


# -- persistence + rendering -----------------------------------------

def doctor_path(results_folder: str) -> str:
    return os.path.join(results_folder, DOCTOR_FILE)


def write_doctor(results_folder: str, doc: dict) -> str:
    """Land a diagnosis as doctor.json (atomic tmp+rename; this module
    is the only writer of that filename)."""
    os.makedirs(results_folder, exist_ok=True)
    path = doctor_path(results_folder)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1)
    os.replace(tmp, path)
    return path


def load_doctor(results_folder: str) -> Optional[dict]:
    try:
        with open(doctor_path(results_folder)) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def render(doc: dict, limit: int = 0) -> str:
    """The ranked findings table `nvs3d obs doctor` prints."""
    findings = doc.get("findings") or []
    if limit:
        findings = findings[:limit]
    lines: List[str] = []
    header = f"doctor ({doc.get('mode', '?')})"
    if doc.get("mode") == "pair":
        header += f": {doc.get('run_a')} → {doc.get('run_b')}"
    elif doc.get("mode") == "trajectory":
        header += f": archive {doc.get('root')}"
    lines.append(header)
    if not findings:
        lines.append("  (no findings — artifacts carry no comparable "
                     "telemetry)")
    for i, f in enumerate(findings, 1):
        sev = f.get("severity", "?").upper()
        lines.append(f"  {i:>2}. [{sev:<4}] {f.get('title', '')}")
        if f.get("detail"):
            lines.append(f"      {f['detail']}")
    return "\n".join(lines)
