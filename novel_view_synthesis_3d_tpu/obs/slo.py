"""Live SLO engine: per-step-class latency objectives with
multi-window burn-rate alerting.

The serving stack's aggregate RPS says nothing about whether the
4-step distilled tier is meeting its 500 ms promise while the 64-step
tier quietly burns its error budget (cf. the Gemma-on-TPU serving
comparison in PAPERS.md, which reports per-class SLO attainment, not
throughput). This module scores every completed/failed request against
a declarative target table (``serve.slo.targets``, e.g.
``"4:500,64:2000"`` — step class → latency budget in ms) and computes
the standard multi-window burn rate:

    burn(window) = error_rate(window) / (1 - objective)

A breach fires only when BOTH the fast window (paging-fast, e.g. 60 s
at 14x) and the slow window (sustained, e.g. 600 s at 2x) exceed their
thresholds — the fast window alone is too noisy at serve-bench request
counts, the slow window alone pages an hour late. Breach and recovery
transitions are emitted as events (``slo_breach`` / ``slo_recovered``)
through whatever callback the owner wires (the service routes them to
the EventBus), and the live values are exported as ``nvs3d_slo_*``
gauges on /metrics.

The clock is injectable so burn-rate dynamics are unit-testable
without sleeping through a 10-minute window.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple


def parse_targets(spec: str) -> Dict[int, float]:
    """``"4:500,64:2000"`` → {4: 0.5, 64: 2.0} (ms in, seconds out).
    Empty/blank spec → {} (engine disabled). Raises ValueError on a
    malformed entry so a config typo fails at startup, not silently."""
    out: Dict[int, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            cls, ms = part.split(":")
            out[int(cls)] = float(ms) / 1000.0
        except Exception:
            raise ValueError(
                f"bad serve.slo.targets entry {part!r} "
                "(want '<steps>:<latency_ms>', e.g. '4:500,64:2000')")
    return out


class SLOEngine:
    """Scores request completions against per-step-class objectives.

    ``record(steps, latency_s, ok=...)`` is the whole producer surface:
    the service calls it once per resolved/failed request. Everything
    else (burn windows, gauges, breach events) is derived."""

    def __init__(self, *, targets: Dict[int, float],
                 objective: float = 0.99,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 600.0,
                 fast_burn: float = 14.0,
                 slow_burn: float = 2.0,
                 registry=None,
                 event_cb: Optional[Callable[[str, str], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.targets = dict(targets)
        self.objective = min(max(float(objective), 0.0), 0.999999)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self._clock = clock
        self._event_cb = event_cb
        self._lock = threading.Lock()
        # per class: deque of (t, good) — pruned past the slow window
        self._samples: Dict[int, "collections.deque"] = {
            cls: collections.deque() for cls in self.targets}
        self._breached: Dict[int, bool] = {cls: False
                                           for cls in self.targets}
        self._g_attain = self._g_burn = self._g_breach = None
        if registry is not None and self.targets:
            self._g_attain = registry.gauge(
                "nvs3d_slo_attainment",
                "fraction of requests meeting their latency target "
                "(slow window)")
            self._g_burn = registry.gauge(
                "nvs3d_slo_burn_rate",
                "error-budget burn rate per step class and window")
            self._g_breach = registry.gauge(
                "nvs3d_slo_breach",
                "1 while a step class is in multi-window breach")

    @property
    def enabled(self) -> bool:
        return bool(self.targets)

    def classify(self, steps: int) -> Optional[int]:
        """Map a request's step count onto a target class: exact match,
        else the smallest class that covers it, else the largest (a
        1024-step request is judged against the loosest budget rather
        than dropped from the books)."""
        if not self.targets:
            return None
        if steps in self.targets:
            return steps
        above = [c for c in self.targets if c >= steps]
        return min(above) if above else max(self.targets)

    # -- producer surface ----------------------------------------------
    def record(self, steps: int, latency_s: float, *,
               ok: bool = True) -> None:
        """Score one finished request. ``ok=False`` (anomaly, expiry,
        worker failure) always burns budget; an ok request burns when
        it misses its class's latency budget."""
        cls = self.classify(int(steps))
        if cls is None:
            return
        good = bool(ok) and float(latency_s) <= self.targets[cls]
        now = self._clock()
        with self._lock:
            dq = self._samples[cls]
            dq.append((now, good, float(latency_s)))
            cutoff = now - self.slow_window_s
            while dq and dq[0][0] < cutoff:
                dq.popleft()
        self._evaluate(cls, now)

    # -- derived state -------------------------------------------------
    def _window_stats(self, cls: int, window_s: float,
                      now: float) -> Tuple[int, int]:
        """(total, errors) over the trailing window for one class."""
        cutoff = now - window_s
        with self._lock:
            samples = [s for s in self._samples[cls] if s[0] >= cutoff]
        return len(samples), sum(1 for s in samples if not s[1])

    def latency_p99(self, window_s: Optional[float] = None,
                    now: Optional[float] = None) -> float:
        """p99 request latency (seconds) over the trailing window,
        across ALL step classes — the polled gray-failure gauge
        (/healthz ``latency_p99_s``) the fleet router's demotion policy
        compares across replicas. 0.0 with no samples."""
        now = self._clock() if now is None else now
        window_s = self.slow_window_s if window_s is None else window_s
        cutoff = now - window_s
        with self._lock:
            lats = sorted(s[2] for dq in self._samples.values()
                          for s in dq if s[0] >= cutoff)
        if not lats:
            return 0.0
        idx = max(0, -(-99 * len(lats) // 100) - 1)  # ceil(.99n) - 1
        return float(lats[idx])

    def burn_rate(self, cls: int, window_s: float,
                  now: Optional[float] = None) -> float:
        now = self._clock() if now is None else now
        total, errors = self._window_stats(cls, window_s, now)
        if total == 0:
            return 0.0
        return (errors / total) / (1.0 - self.objective)

    def _evaluate(self, cls: int, now: float) -> None:
        fast = self.burn_rate(cls, self.fast_window_s, now)
        slow = self.burn_rate(cls, self.slow_window_s, now)
        total, errors = self._window_stats(cls, self.slow_window_s, now)
        attain = 1.0 - (errors / total) if total else 1.0
        breached = fast >= self.fast_burn and slow >= self.slow_burn
        if self._g_attain is not None:
            label = str(cls)
            self._g_attain.set(attain, step_class=label)
            self._g_burn.set(fast, step_class=label, window="fast")
            self._g_burn.set(slow, step_class=label, window="slow")
            self._g_breach.set(1.0 if breached else 0.0,
                               step_class=label)
        with self._lock:
            was = self._breached[cls]
            self._breached[cls] = breached
        if breached != was and self._event_cb is not None:
            try:
                kind = "slo_breach" if breached else "slo_recovered"
                self._event_cb(kind,
                               f"class={cls} fast_burn={fast:.1f} "
                               f"slow_burn={slow:.1f} "
                               f"attainment={attain:.4f}")
            except Exception:
                pass  # alerting faults must not take down serving

    def snapshot(self) -> Dict[str, dict]:
        """Per-class attainment/burn summary for service.summary(),
        serve_bench artifacts, and ``nvs3d obs slo``."""
        now = self._clock()
        out: Dict[str, dict] = {}
        for cls in sorted(self.targets):
            total, errors = self._window_stats(
                cls, self.slow_window_s, now)
            out[str(cls)] = {
                "target_ms": round(self.targets[cls] * 1000.0, 3),
                "objective": self.objective,
                "total": total,
                "errors": errors,
                "attainment": (1.0 - errors / total) if total else 1.0,
                "fast_burn": self.burn_rate(cls, self.fast_window_s,
                                            now),
                "slow_burn": self.burn_rate(cls, self.slow_window_s,
                                            now),
                "breached": self._breached[cls],
            }
        return out


def attainment_from_rows(rows: List[dict],
                         targets: Dict[int, float]) -> Dict[str, dict]:
    """Offline SLO attainment over telemetry.jsonl span rows — the
    whole-run view behind ``nvs3d obs slo`` (the live engine only sees
    its sliding window). Scores ``request_respond`` spans: latency from
    ``latency_s``, class from ``steps``, error when outcome != 'ok'."""
    eng = SLOEngine(targets=targets, slow_window_s=float("inf"),
                    clock=lambda: 0.0)
    for row in rows:
        if row.get("kind") != "span" or row.get(
                "name") != "request_respond":
            continue
        try:
            eng.record(int(row.get("steps", 0)),
                       float(row.get("latency_s", 0.0)),
                       ok=row.get("outcome") == "ok")
        except (TypeError, ValueError):
            continue
    return eng.snapshot()


def fleet_attainment(per_source: Dict[str, List[dict]],
                     targets: Dict[int, float]) -> Dict[str, dict]:
    """Offline SLO attainment across a fleet: `per_source` is the
    {source: rows} map from ``reqtrace.load_fleet_rows``. Each
    ``replica_<name>`` source is scored on its own request_respond
    spans; the ``"fleet"`` rollup re-scores the union, so it is
    traffic-weighted rather than a mean of per-replica attainments
    (a near-idle replica cannot mask a busy one's misses)."""
    out: Dict[str, dict] = {}
    merged: List[dict] = []
    for source, rows in sorted(per_source.items()):
        if not source.startswith("replica_"):
            continue
        out[source] = attainment_from_rows(rows, targets)
        merged.extend(rows)
    out["fleet"] = attainment_from_rows(merged, targets)
    return out
