"""RunIndex: append-only index over the banked perf artifacts.

Every trajectory question so far (bench_sentry, summarize_bench, humans)
re-globbed ``BENCH_r*.json`` and re-parsed every round from scratch. The
index scans once and appends one JSONL entry per NEW or CHANGED artifact
to ``results/runindex.jsonl`` (keyed by path + mtime + size, so a
re-banked round re-indexes); queries then read the index, not the tree.
Entries carry just enough to rank without re-opening the bank —
rc / vs_baseline / ok — while ``load_doc`` fetches the full JSON when
the doctor needs spans or costmaps.

This module is the ONLY place that names ``runindex.jsonl`` (the same
single-writer conformance the bus enforces for telemetry files). The
index is derived state: deleting it merely costs one rescan, so it is
gitignored, and an unwritable results/ degrades to an in-memory index
rather than an error. No jax anywhere — the doctor runs on machines
that never ran the job.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional

_INDEX_FILE = "runindex.jsonl"
_ROUND_RE = re.compile(r"^([A-Z][A-Z0-9_]*)_r(\d+)\.json$")
# Files that mark a directory as a run folder worth indexing.
_RUN_ARTIFACTS = ("telemetry.jsonl", "metrics.csv", "costmap.json",
                  "compiles.jsonl", "doctor.json")


def runindex_path(root: str) -> str:
    return os.path.join(root, "results", _INDEX_FILE)


def _round_entry(root: str, fname: str) -> Optional[dict]:
    m = _ROUND_RE.match(fname)
    if not m:
        return None
    path = os.path.join(root, fname)
    try:
        st = os.stat(path)
    except OSError:
        return None
    entry = {"kind": "round", "prefix": m.group(1),
             "round": int(m.group(2)), "path": fname,
             "mtime": round(st.st_mtime, 3), "size": st.st_size}
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        entry["torn"] = True
        return entry
    if isinstance(doc, dict):
        for key in ("rc", "vs_baseline", "ok", "lane", "preset",
                    "steps_per_sec"):
            if key in doc:
                entry[key] = doc[key]
    return entry


def _run_dir_entry(root: str, rel: str) -> Optional[dict]:
    d = os.path.join(root, rel)
    artifacts = [a for a in _RUN_ARTIFACTS
                 if os.path.exists(os.path.join(d, a))]
    if not artifacts:
        return None
    newest = max(os.path.getmtime(os.path.join(d, a)) for a in artifacts)
    size = sum(os.path.getsize(os.path.join(d, a)) for a in artifacts)
    return {"kind": "run_dir", "path": rel, "artifacts": artifacts,
            "mtime": round(newest, 3), "size": size}


class RunIndex:
    """Index over one archive root (the repo root in the banked layout:
    BENCH_r*/MULTICHIP_r* at top level, run folders under results/)."""

    def __init__(self, root: str = "."):
        self.root = root
        self.path = runindex_path(root)

    # -- persistence ---------------------------------------------------
    def _read(self) -> Dict[str, dict]:
        """Last indexed entry per path (later lines supersede)."""
        out: Dict[str, dict] = {}
        if not os.path.exists(self.path):
            return out
        with open(self.path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                except ValueError:
                    continue  # torn tail, crash-tolerant like the ledger
                if isinstance(entry, dict) and "path" in entry:
                    out[entry["path"]] = entry
        return out

    def _scan(self) -> List[dict]:
        entries: List[dict] = []
        try:
            top = sorted(os.listdir(self.root))
        except OSError:
            return entries
        for fname in top:
            e = _round_entry(self.root, fname)
            if e is not None:
                entries.append(e)
        results = os.path.join(self.root, "results")
        if os.path.isdir(results):
            e = _run_dir_entry(self.root, "results")
            if e is not None:
                entries.append(e)
            for sub in sorted(os.listdir(results)):
                rel = os.path.join("results", sub)
                if os.path.isdir(os.path.join(self.root, rel)):
                    e = _run_dir_entry(self.root, rel)
                    if e is not None:
                        entries.append(e)
        return entries

    def refresh(self) -> List[dict]:
        """Scan the tree, append entries for new/changed artifacts, and
        return the CURRENT full entry list. A read-only results/ keeps
        the scan result in memory (index file simply not advanced)."""
        known = self._read()
        scanned = self._scan()
        fresh = [e for e in scanned
                 if known.get(e["path"], {}).get("mtime") != e["mtime"]
                 or known.get(e["path"], {}).get("size") != e["size"]]
        if fresh:
            try:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                with open(self.path, "a") as fh:
                    for e in fresh:
                        fh.write(json.dumps(e) + "\n")
            except OSError:
                pass  # derived state; in-memory result still correct
        return scanned

    # -- queries -------------------------------------------------------
    def rounds(self, prefix: str = "BENCH") -> List[dict]:
        """Indexed round entries for one bank prefix, round-ordered."""
        entries = [e for e in self.refresh()
                   if e.get("kind") == "round"
                   and e.get("prefix") == prefix]
        entries.sort(key=lambda e: e["round"])
        return entries

    def run_dirs(self) -> List[dict]:
        return [e for e in self.refresh() if e.get("kind") == "run_dir"]

    def load_doc(self, entry: dict) -> Optional[dict]:
        """Full JSON for a round entry; None on torn files."""
        try:
            with open(os.path.join(self.root, entry["path"])) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return None
        return doc if isinstance(doc, dict) else None
