"""Utilization gauges: device memory polling and MFU.

Answers "is HBM creeping" and "how much of the chip are we using" on a
LIVE run without attaching a debugger:

  - ``DeviceMonitor``: a daemon thread polling ``device.memory_stats()``
    for every local device on a period (``obs.device_poll_s``), feeding
    ``nvs3d_device_bytes_in_use / _device_peak_bytes / _device_bytes_limit``
    gauges (labeled per device) plus ``nvs3d_host_rss_bytes``. Backends
    whose devices report no memory stats (CPU) fall back to host RSS
    under a ``source="host_rss"`` label so the gauge family — and any
    dashboard built on it — exists on every platform. Each poll also
    mirrors to the JSONL sink so `tools/summarize_bench.py` can report
    peak HBM after the fact.
  - ``device_peak_flops()``: dense-bf16 peak per chip from public spec
    sheets, keyed on ``device_kind`` (the one home for this table —
    bench.py and the trainer's MFU gauge both read it).
  - ``mfu(...)``: model-FLOPs-utilization from a one-time
    ``jax.jit(...).lower().cost_analysis()`` FLOPs estimate and the
    observed step rate. cost_analysis() reports whole-program FLOPs on
    SPMD executables in the pinned JAX, so MFU normalizes by
    peak × n_chips; on one chip the conventions coincide.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

# Dense bf16 peak FLOPs per chip, public spec sheets. v5e/v5litepod:
# 197 TF (394 is its int8 TOPS figure, not bf16); v4: 275 TF;
# v6e/trillium: 918 TF. Unknown kinds return None — an absent MFU beats
# one silently computed against the wrong peak.
_PEAK_FLOPS_BY_KIND = (("v5lite", 197e12), ("v5e", 197e12),
                       ("v6", 918e12), ("v4", 275e12))

# HBM bandwidth per chip, same spec sheets and keying: v5e/v5litepod
# 819 GB/s, v4 1228 GB/s, v6e/trillium 1640 GB/s. The roofline join
# (obs/roofline.py) divides by this to classify memory-bound groups —
# this table is its one home, next to the FLOPs peaks it pairs with.
_PEAK_BYTES_BY_KIND = (("v5lite", 819e9), ("v5e", 819e9),
                       ("v6", 1640e9), ("v4", 1228e9))


def _peak_by_kind(table, device) -> Optional[float]:
    import jax

    if device is None:
        devices = jax.devices()
        if not devices:
            return None
        device = devices[0]
    kind = device.device_kind.lower().replace(" ", "")
    return next((v for k, v in table if k in kind), None)


def device_peak_flops(device=None) -> Optional[float]:
    """Dense bf16 peak FLOPs/s for one chip, or None if unknown."""
    return _peak_by_kind(_PEAK_FLOPS_BY_KIND, device)


def device_peak_bytes_per_s(device=None) -> Optional[float]:
    """Peak HBM bytes/s for one chip, or None if unknown (CPU)."""
    return _peak_by_kind(_PEAK_BYTES_BY_KIND, device)


def mfu(flops_per_step: float, steps_per_sec: float,
        n_chips: Optional[int] = None) -> Optional[float]:
    """Model-FLOPs utilization in [0, 1], or None when the chip's peak is
    unknown (CPU, unrecognized TPU generation)."""
    import jax

    peak = device_peak_flops()
    if not peak or not flops_per_step or steps_per_sec <= 0:
        return None
    if n_chips is None:
        n_chips = max(1, len(jax.devices()))
    return flops_per_step * steps_per_sec / (peak * n_chips)


def host_rss_bytes() -> Optional[int]:
    """Resident set size of this process, Linux-first with a stdlib
    fallback (ru_maxrss is a PEAK, labeled as such by the caller)."""
    try:
        with open("/proc/self/statm") as fh:
            pages = int(fh.read().split()[1])
        import os

        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        try:
            import resource

            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:
            return None


def read_device_memory() -> List[dict]:
    """One sample per local device that answers memory_stats():
    {device, bytes_in_use, peak_bytes_in_use, bytes_limit} (absent keys
    omitted). Empty on backends without the API (CPU)."""
    import jax

    out = []
    for d in jax.local_devices():
        stats = getattr(d, "memory_stats", lambda: None)()
        if not stats:
            continue
        sample = {"device": str(d.id)}
        for key, stat in (("bytes_in_use", "bytes_in_use"),
                          ("peak_bytes_in_use", "peak_bytes_in_use"),
                          ("bytes_limit", "bytes_limit")):
            if stat in stats:
                sample[key] = int(stats[stat])
        out.append(sample)
    return out


class DeviceMonitor:
    """Periodic device-memory poller feeding the registry (and JSONL).

    `poll()` is also callable directly (bench snapshots, tests). The
    thread is a daemon sleeping on an Event — stop() is prompt, and a
    wedged backend can't block interpreter exit. Polling cost is one
    memory_stats() call per device per period (a local PJRT query, no
    device sync); the default 10 s period is invisible next to a step.
    """

    def __init__(self, registry, *, poll_s: float = 10.0,
                 jsonl_cb: Optional[Callable[..., None]] = None):
        self.registry = registry
        self.poll_s = poll_s
        self._jsonl_cb = jsonl_cb
        self._in_use = registry.gauge(
            "nvs3d_device_bytes_in_use",
            "device memory currently allocated, per local device "
            "(host RSS under source=\"host_rss\" when the backend "
            "reports no device stats)")
        self._peak = registry.gauge(
            "nvs3d_device_peak_bytes",
            "high-water device memory since process start, per device")
        self._limit = registry.gauge(
            "nvs3d_device_bytes_limit",
            "allocatable device memory, per device")
        self._rss = registry.gauge(
            "nvs3d_host_rss_bytes", "host process resident set size")
        self.peak_bytes = 0  # run-level high water across devices
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll(self) -> List[dict]:
        samples = read_device_memory()
        for s in samples:
            dev = s["device"]
            if "bytes_in_use" in s:
                self._in_use.set(s["bytes_in_use"], device=dev)
                self.peak_bytes = max(self.peak_bytes, s["bytes_in_use"])
            if "peak_bytes_in_use" in s:
                self._peak.set(s["peak_bytes_in_use"], device=dev)
                self.peak_bytes = max(self.peak_bytes,
                                      s["peak_bytes_in_use"])
            if "bytes_limit" in s:
                self._limit.set(s["bytes_limit"], device=dev)
        rss = host_rss_bytes()
        if rss is not None:
            self._rss.set(rss)
            if not samples:
                # CPU (or any backend without memory_stats): keep the
                # device gauge family alive with the host number, loudly
                # labeled — dashboards stay wired, nobody mistakes it for
                # HBM.
                self._in_use.set(rss, device="host", source="host_rss")
                self.peak_bytes = max(self.peak_bytes, rss)
        if self._jsonl_cb is not None and (samples or rss is not None):
            self._jsonl_cb("nvs3d_device_peak_bytes", self.peak_bytes,
                           scope="run_max")
        return samples

    def snapshot(self) -> dict:
        """Point-in-time summary for bench JSON embedding."""
        samples = self.poll()
        out: dict = {"peak_bytes": self.peak_bytes}
        if samples:
            out["devices"] = samples
        rss = host_rss_bytes()
        if rss is not None:
            out["host_rss_bytes"] = rss
        return out

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "DeviceMonitor":
        if self._thread is None and self.poll_s > 0:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="obs-devmon")
            self._thread.start()
        return self

    def _run(self) -> None:
        # Immediate first sample: a run shorter than one period still
        # reports memory.
        try:
            self.poll()
        except Exception:
            pass
        while not self._stop.wait(self.poll_s):
            try:
                self.poll()
            except Exception:
                pass  # a flaky backend query must never kill telemetry

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
