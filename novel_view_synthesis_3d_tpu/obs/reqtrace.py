"""Request-scoped tracing: mint, thread, and reconstruct per-request
causal timelines through the serving stack.

The PR 4 tracer sees *phases* (queue_wait, batch_form, ring_step); a
latency postmortem needs *requests*: which dispatches did request 17
ride, who were its co-riders, how much step debt did its ring carry,
did a model swap drain or a brownout decision sit in its path. This
module defines the contract that makes that reconstructable from
telemetry.jsonl alone, without touching device programs (program
identity, bit-identity, and the zero-recompile contract are host-side
invariants this layer must not disturb).

Trace-context contract (all attrs ride the existing
``bus.span_record`` scalar-attr path — nothing new on the wire):

  - ``request_submit`` (zero-duration marker, emitted at admission):
    ``trace_id`` (client-suppliable via the serve JSONL schema, else
    minted from the request id), ``span_id`` (the causal root),
    ``request_id``, ``req_kind`` ('single' | 'trajectory'),
    ``steps``, ``brownout`` (ladder level at admission), and for
    trajectories ``frames``.
  - request-scoped child spans (``queue_wait``, ``step_wait``,
    ``trajectory_frame``, ``cond_cache``) carry ``trace_id`` +
    ``parent_id`` pointing at the root ``span_id``. ``cond_cache``
    (PR 18, emitted at admission when the conditioning cache is on)
    carries ``uncond`` ('hit' | 'miss' for the shared per-resolution
    uncond entry) and ``bytes`` (device-resident cache size for this
    request).
  - shared dispatch spans (``ring_step`` / ``compile`` in the stepper
    ring, ``device`` in the request scheduler) carry ``dispatch`` (a
    service-global ordinal), ``riders`` (comma-joined request ids —
    one row per dispatch, NOT one per rider, so tracing cost does not
    scale with batch size), and ``debt`` (the ring's step debt).
  - ``request_respond`` (retrospective span covering submit→response):
    ``trace_id``, ``parent_id``, ``outcome`` ('ok' | 'anomaly' |
    'expired' | 'failed'), ``latency_s``, ``dispatches`` (rides
    counted by the service — reconstruction cross-checks it),
    ``swap_drains`` (param swaps that drained between submit and
    admission), ``steps``, and for trajectories ``frames_done``.

Everything below `load_rows` is the offline half: ``nvs3d obs trace``
(timeline reconstruction + per-request Perfetto track), ``nvs3d obs
diff`` (span-percentile drift between runs), and the serve_bench
reqtrace assertions all run on these functions, so the CLI and the
bench judge the exact same reconstruction the tests pin down.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Tuple

from . import bus as _bus

_SAFE = re.compile(r"[^A-Za-z0-9._-]")

# Span names whose rows are request-scoped (carry trace_id) vs shared
# dispatch rows (carry riders). Reconstruction keys off these. A cold
# dispatch is named "compile" in both schedulers (the PR 3 convention)
# but is still a dispatch its riders rode.
REQUEST_SPAN_NAMES = ("queue_wait", "step_wait", "trajectory_frame",
                      "cond_cache")
DISPATCH_SPAN_NAMES = ("ring_step", "device", "compile")


def mint(request_id: int, client: Optional[str] = None) -> str:
    """Trace id for one request: the client's (sanitized to
    ``[A-Za-z0-9._-]{1,64}`` so it is safe in filenames and CSV cells)
    or a deterministic run-local default."""
    if client:
        safe = _SAFE.sub("_", str(client))[:64]
        if safe:
            return safe
    return f"t-{int(request_id)}"


def root_span_id(trace_id: str) -> str:
    return f"{trace_id}/0"


# ---------------------------------------------------------------------------
# Offline reconstruction (telemetry.jsonl → per-request timelines)
# ---------------------------------------------------------------------------
def load_rows(run_dir: str) -> List[dict]:
    """All telemetry rows for a run dir, oldest first — reads the
    rotated-aside ``telemetry.jsonl.old`` (if any) before the live
    file, so a run that crossed the size cap still reconstructs."""
    rows: List[dict] = []
    live = _bus.jsonl_path(run_dir)
    for path in (live + ".old", live):
        if not os.path.exists(path):
            continue
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue  # torn tail line from a crash
    return rows


def _riders_of(row: dict) -> List[int]:
    out = []
    for part in str(row.get("riders", "")).split(","):
        part = part.strip()
        if part:
            try:
                out.append(int(part))
            except ValueError:
                pass
    return out


def _id_scope(row: dict):
    """Request ids and dispatch ordinals are PROCESS-local counters. A
    supervised respawn (serve/fleet_supervisor.py) appends to its dead
    predecessor's telemetry.jsonl, so one file can hold two incarnations
    both counting from zero — rider joins must stay within the writing
    process. Rows predating the pid stamp share one scope (None), which
    is exactly the old behavior."""
    return row.get("pid")


def reconstruct(rows: List[dict]) -> Dict[str, dict]:
    """telemetry rows → {trace_id: timeline}. A timeline is complete
    when both its root (``request_submit``) and its ``request_respond``
    landed; dispatch rows attach to every rider's timeline with the
    co-rider count observed on that dispatch. Rider joins are scoped
    per writing process (see ``_id_scope``)."""
    timelines: Dict[str, dict] = {}
    by_request: Dict[tuple, str] = {}
    spans = [r for r in rows if r.get("kind") == "span"]
    for row in spans:
        if row.get("name") != "request_submit":
            continue
        tid = str(row.get("trace_id", ""))
        if not tid:
            continue
        rid = int(row.get("request_id", -1))
        timelines[tid] = {
            "trace_id": tid,
            "request_id": rid,
            "id_scope": _id_scope(row),
            "req_kind": row.get("req_kind", "single"),
            "steps": row.get("steps"),
            "frames": row.get("frames"),
            "brownout": row.get("brownout"),
            "submit_t": row.get("t"),
            "spans": [],
            "dispatches": [],
            "respond": None,
        }
        by_request[(_id_scope(row), rid)] = tid
    for row in spans:
        name = row.get("name")
        tid = str(row.get("trace_id", ""))
        if name == "request_respond" and tid in timelines:
            timelines[tid]["respond"] = row
        elif name in REQUEST_SPAN_NAMES and tid in timelines:
            timelines[tid]["spans"].append(row)
        elif name in DISPATCH_SPAN_NAMES and "riders" in row:
            riders = _riders_of(row)
            for rid in riders:
                tid = by_request.get((_id_scope(row), rid))
                if tid is None:
                    continue
                timelines[tid]["dispatches"].append({
                    "dispatch": row.get("dispatch"),
                    "name": name,
                    "t": row.get("t"),
                    "dur_s": row.get("dur_s"),
                    "co_riders": len(riders),
                    "debt": row.get("debt"),
                    "bucket": row.get("bucket"),
                })
    for tl in timelines.values():
        tl["spans"].sort(key=lambda r: r.get("t") or 0.0)
        tl["dispatches"].sort(key=lambda d: (d["dispatch"] is None,
                                             d["dispatch"]))
        tl["complete"] = tl["respond"] is not None
        tl["outcome"] = (tl["respond"] or {}).get("outcome")
    return timelines


def verify_timelines(timelines: Dict[str, dict],
                     rows: List[dict]) -> List[str]:
    """Invariant check behind the serve_bench reqtrace assertion and
    the tier-1 reconstruction test. Returns human-readable problems
    (empty == the trace is sound):

      - every request that responded has a causal chain back to a
        submit root (guaranteed by construction) and, when it did work
        on-device, at least one dispatch;
      - no dispatch ordinal appears twice in one request's timeline
        (a request rides each dispatch exactly once);
      - the service's own ride count (``dispatches`` on the respond
        span) agrees with reconstruction;
      - every rider named on a dispatch row maps to a known submit.
    """
    problems: List[str] = []
    known = {(tl.get("id_scope"), tl["request_id"])
             for tl in timelines.values()}
    for row in rows:
        if row.get("kind") != "span" or "riders" not in row:
            continue
        if row.get("name") not in DISPATCH_SPAN_NAMES:
            continue
        for rid in _riders_of(row):
            if (_id_scope(row), rid) not in known:
                problems.append(
                    f"dispatch {row.get('dispatch')} names rider "
                    f"{rid} with no request_submit root")
    for tid, tl in sorted(timelines.items()):
        ords = [d["dispatch"] for d in tl["dispatches"]
                if d["dispatch"] is not None]
        if len(ords) != len(set(ords)):
            problems.append(f"{tid}: dispatch ordinal appears twice "
                            f"in one timeline ({sorted(ords)})")
        resp = tl["respond"]
        if resp is None:
            continue
        claimed = resp.get("dispatches")
        if claimed is not None and int(claimed) != len(ords):
            problems.append(
                f"{tid}: service counted {claimed} rides, "
                f"reconstruction found {len(ords)}")
        if resp.get("outcome") == "ok" and claimed and not ords:
            problems.append(f"{tid}: responded ok after "
                            f"{claimed} rides but no dispatch row "
                            "names it as a rider")
    return problems


def format_timeline(tl: dict) -> str:
    """One request's story as text — the ``nvs3d obs trace`` output."""
    lines = [
        f"trace {tl['trace_id']}  request_id={tl['request_id']}  "
        f"kind={tl['req_kind']}  steps={tl.get('steps')}"
        + (f"  frames={tl['frames']}" if tl.get("frames") else "")
        + (f"  brownout={tl['brownout']}" if tl.get("brownout")
           else "")]
    t0 = tl.get("submit_t") or 0.0

    def rel(t):
        return f"+{(t or t0) - t0:8.3f}s"

    lines.append(f"  {rel(t0)}  submit")
    merged: List[Tuple[float, str]] = []
    for row in tl["spans"]:
        extra = ""
        if row.get("name") == "trajectory_frame":
            extra = f" frame={row.get('frame_index')}"
        merged.append((row.get("t") or t0,
                       f"{row['name']}{extra} "
                       f"dur={1e3 * (row.get('dur_s') or 0.0):.1f}ms"))
    for d in tl["dispatches"]:
        merged.append((d.get("t") or t0,
                       f"{d['name']} #{d['dispatch']} "
                       f"co_riders={d['co_riders']} "
                       f"debt={d.get('debt')} "
                       f"dur={1e3 * (d.get('dur_s') or 0.0):.1f}ms"))
    for t, text in sorted(merged, key=lambda p: p[0]):
        lines.append(f"  {rel(t)}  {text}")
    resp = tl.get("respond")
    if resp is None:
        lines.append("  [incomplete: no request_respond recorded]")
    else:
        lines.append(
            f"  {rel(resp.get('t'))}  respond outcome={resp.get('outcome')} "
            f"latency={1e3 * (resp.get('latency_s') or 0.0):.1f}ms "
            f"rides={resp.get('dispatches')} "
            f"swap_drains={resp.get('swap_drains')}")
    return "\n".join(lines)


def export_perfetto(tl: dict, path: str) -> str:
    """One request's timeline as a Chrome-trace file: a single track
    whose ``X`` events are the request's spans and the dispatches it
    rode — the per-request counterpart of the run-wide trace.json."""
    t0 = tl.get("submit_t") or 0.0
    events = [{"ph": "M", "name": "process_name", "pid": 0, "tid": 0,
               "args": {"name": f"request[{tl['trace_id']}]"}}]

    def ev(name, t, dur_s, **args):
        events.append({"ph": "X", "name": name, "pid": 0, "tid": 0,
                       "ts": max(0.0, ((t or t0) - t0)) * 1e6,
                       "dur": max(0.0, dur_s or 0.0) * 1e6,
                       "args": args})

    ev("request_submit", t0, 0.0, trace_id=tl["trace_id"],
       request_id=tl["request_id"], req_kind=tl["req_kind"])
    for row in tl["spans"]:
        # dur'd spans END at their stamp; draw them leading up to it.
        t_end = row.get("t") or t0
        dur = row.get("dur_s") or 0.0
        ev(row["name"], t_end - dur, dur,
           **{k: v for k, v in row.items()
              if k not in ("kind", "name", "t", "dur_s")
              and isinstance(v, (int, float, str, bool))})
    for d in tl["dispatches"]:
        t_end = d.get("t") or t0
        dur = d.get("dur_s") or 0.0
        ev(f"{d['name']}#{d['dispatch']}", t_end - dur, dur,
           co_riders=d["co_riders"], debt=d.get("debt"),
           bucket=d.get("bucket"))
    resp = tl.get("respond")
    if resp is not None:
        ev("request_respond", (resp.get("t") or t0)
           - (resp.get("latency_s") or 0.0), resp.get("latency_s"),
           outcome=resp.get("outcome"),
           dispatches=resp.get("dispatches"))
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as fh:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, fh)
    return path


# ---------------------------------------------------------------------------
# Fleet reconstruction (router + N replica run dirs → cross-replica
# timelines). Request ids are SERVICE-LOCAL (each replica numbers its
# own), so the merge key is the trace_id the router threads through
# every hop: reconstruct each replica's telemetry independently, then
# join replica timelines onto the router's hop records by trace_id.
# ---------------------------------------------------------------------------
ROUTER_SPAN_NAMES = ("router_submit", "router_hop", "router_hedge",
                     "router_respond")


def load_fleet_rows(fleet_dir: str) -> Dict[str, List[dict]]:
    """Per-source telemetry rows for a fleet run dir laid out as
    ``<fleet_dir>/router/`` + ``<fleet_dir>/replica_<name>/`` (the
    serve_bench --fleet / `nvs3d route` convention). Sources with no
    telemetry file are omitted; an empty result means `fleet_dir` is
    not a fleet dir."""
    out: Dict[str, List[dict]] = {}
    try:
        entries = sorted(os.listdir(fleet_dir))
    except OSError:
        return out
    for entry in entries:
        if entry != "router" and not entry.startswith("replica_"):
            continue
        sub = os.path.join(fleet_dir, entry)
        if not os.path.isdir(sub):
            continue
        rows = load_rows(sub)
        if rows:
            out[entry] = rows
    return out


def reconstruct_fleet(per_source: Dict[str, List[dict]]
                      ) -> Dict[str, dict]:
    """{source: rows} → {trace_id: fleet timeline}. A fleet timeline is
    the router's view (root + one record per hop + respond) with each
    replica's OWN reconstructed timeline for that trace attached under
    ``replica_timelines[replica]`` — the cross-replica story
    `nvs3d obs trace` prints after a failover."""
    fleet: Dict[str, dict] = {}
    router_rows = per_source.get("router", [])
    for row in router_rows:
        if row.get("kind") != "span" \
                or row.get("name") != "router_submit":
            continue
        tid = str(row.get("trace_id", ""))
        if tid:
            fleet[tid] = {
                "trace_id": tid,
                "req_kind": row.get("req_kind", "single"),
                "steps": row.get("steps"),
                "frames": row.get("frames"),
                "session": row.get("session"),
                "submit_t": row.get("t"),
                "hops": [],
                "hedges": [],
                "respond": None,
                "replica_timelines": {},
            }
    for row in router_rows:
        if row.get("kind") != "span":
            continue
        tid = str(row.get("trace_id", ""))
        if tid not in fleet:
            continue
        if row.get("name") == "router_hop":
            fleet[tid]["hops"].append(row)
        elif row.get("name") == "router_hedge":
            fleet[tid]["hedges"].append(row)
        elif row.get("name") == "router_respond":
            fleet[tid]["respond"] = row
    for source, rows in per_source.items():
        if not source.startswith("replica_"):
            continue
        replica = source[len("replica_"):]
        for tid, tl in reconstruct(rows).items():
            if tid in fleet:
                fleet[tid]["replica_timelines"][replica] = tl
    for tl in fleet.values():
        tl["hops"].sort(key=lambda h: int(h.get("attempt") or 0))
        tl["complete"] = tl["respond"] is not None
        tl["outcome"] = (tl["respond"] or {}).get("outcome")
        tl["failovers"] = (tl["respond"] or {}).get("failovers")
    return fleet


def verify_fleet(fleet: Dict[str, dict],
                 per_source: Dict[str, List[dict]]) -> List[str]:
    """Fleet-level invariants (the serve_bench --fleet chaos assertion
    and the tier-1 fleet reconstruction test both run THIS):

      - every routed request that responded ok ends on an ok hop, and
        its hop count/failover count agree with the respond span;
      - every ok hop lands on a replica whose own telemetry (when
        present) holds a COMPLETE timeline for that trace — the
        cross-replica join actually closes;
      - each replica's own timelines are individually sound
        (verify_timelines), problems prefixed with the source.
    """
    problems: List[str] = []
    for tid, tl in sorted(fleet.items()):
        resp = tl["respond"]
        if resp is None:
            problems.append(f"{tid}: no router_respond recorded")
            continue
        hops = tl["hops"]
        claimed = resp.get("hops")
        if claimed is not None and int(claimed) != len(hops):
            problems.append(
                f"{tid}: router counted {claimed} hops, "
                f"reconstruction found {len(hops)}")
        fo = resp.get("failovers")
        observed_fo = sum(1 for h in hops
                          if h.get("outcome") == "failover")
        if fo is not None and int(fo) != observed_fo:
            problems.append(
                f"{tid}: respond says {fo} failovers, hops show "
                f"{observed_fo}")
        if resp.get("outcome") == "ok":
            # Hedged dispatch means the winning hop need not be the
            # LAST by attempt ordinal (an abandoned hedge loser's span
            # lands after the winner's) — require one ok hop and only
            # benign non-ok outcomes alongside it.
            if not any(h.get("outcome") == "ok" for h in hops):
                problems.append(
                    f"{tid}: responded ok but no ok hop recorded")
            benign = ("ok", "failover", "hop_timeout",
                      "hedge_abandoned", "cancelled")
            for hop in hops:
                if hop.get("outcome") not in benign:
                    problems.append(
                        f"{tid}: ok respond with stray hop outcome "
                        f"{hop.get('outcome')}")
                if hop.get("outcome") != "ok":
                    continue
                replica = str(hop.get("replica", ""))
                if f"replica_{replica}" not in per_source:
                    continue  # replica telemetry not collected
                rtl = tl["replica_timelines"].get(replica)
                if rtl is None:
                    problems.append(
                        f"{tid}: ok hop on {replica} but no replica-"
                        "side timeline joined for this trace")
                elif not rtl.get("complete"):
                    problems.append(
                        f"{tid}: replica {replica} timeline for this "
                        "trace is incomplete (no request_respond)")
    for source, rows in sorted(per_source.items()):
        if not source.startswith("replica_"):
            continue
        for problem in verify_timelines(reconstruct(rows), rows):
            problems.append(f"[{source}] {problem}")
    return problems


def format_fleet_timeline(tl: dict) -> str:
    """One routed request's cross-replica story as text."""
    head = (f"trace {tl['trace_id']}  kind={tl['req_kind']}  "
            f"steps={tl.get('steps')}")
    if tl.get("frames"):
        head += f"  frames={tl['frames']}"
    if tl.get("session"):
        head += f"  session={tl['session']}"
    lines = [head]
    t0 = tl.get("submit_t") or 0.0
    for hop in tl["hops"]:
        extra = ""
        if hop.get("frames_done") is not None:
            extra = f" frames_done={hop['frames_done']}"
        if hop.get("error"):
            extra += f"  [{hop['error']}]"
        lines.append(
            f"  +{(hop.get('t') or t0) - t0:8.3f}s  hop "
            f"#{hop.get('attempt')} -> {hop.get('replica')}  "
            f"outcome={hop.get('outcome')} "
            f"dur={1e3 * (hop.get('dur_s') or 0.0):.1f}ms{extra}")
    resp = tl.get("respond")
    if resp is None:
        lines.append("  [incomplete: no router_respond recorded]")
    else:
        lines.append(
            f"  +{(resp.get('t') or t0) - t0:8.3f}s  respond "
            f"outcome={resp.get('outcome')} "
            f"latency={1e3 * (resp.get('latency_s') or 0.0):.1f}ms "
            f"hops={resp.get('hops')} failovers={resp.get('failovers')}")
    for replica, rtl in sorted(tl["replica_timelines"].items()):
        lines.append(f"  --- replica {replica} "
                     f"(local request_id={rtl['request_id']}) ---")
        for sub in format_timeline(rtl).splitlines()[1:]:
            lines.append("  " + sub)
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Cross-run span-percentile diff (``nvs3d obs diff``)
# ---------------------------------------------------------------------------
def span_percentiles(rows: List[dict]) -> Dict[str, dict]:
    """Per-span-name {count, p50_ms, p90_ms, p99_ms} over a run's
    telemetry rows — same shape as Tracer.summary but computed offline
    so two finished runs can be compared."""
    import numpy as np

    by_name: Dict[str, list] = {}
    for row in rows:
        if row.get("kind") != "span":
            continue
        dur = row.get("dur_s")
        if dur is None:
            continue
        by_name.setdefault(row["name"], []).append(float(dur))
    out = {}
    for name, durs in sorted(by_name.items()):
        arr = np.asarray(durs)
        out[name] = {
            "count": int(arr.size),
            "p50_ms": float(np.percentile(arr, 50) * 1e3),
            "p90_ms": float(np.percentile(arr, 90) * 1e3),
            "p99_ms": float(np.percentile(arr, 99) * 1e3),
        }
    return out


def diff_percentiles(a: Dict[str, dict], b: Dict[str, dict],
                     threshold_pct: float = 20.0) -> List[dict]:
    """Percentile drift B vs A per span name. ``drift`` is set when
    any percentile moved more than threshold_pct in either direction
    (regressions AND suspicious speedups both warrant a look)."""
    out: List[dict] = []
    for name in sorted(set(a) | set(b)):
        ra, rb = a.get(name), b.get(name)
        row = {"name": name, "a": ra, "b": rb, "drift": False,
               "deltas_pct": {}}
        if ra is None or rb is None:
            row["drift"] = True
            row["note"] = ("only in B" if ra is None else "only in A")
        else:
            for key in ("p50_ms", "p90_ms", "p99_ms"):
                base = ra[key]
                if base <= 0.0:
                    continue
                pct = 100.0 * (rb[key] - base) / base
                row["deltas_pct"][key] = round(pct, 1)
                if abs(pct) > threshold_pct:
                    row["drift"] = True
        out.append(row)
    return out
