"""Prometheus text-exposition HTTP endpoint (`/metrics` + `/healthz`).

Stdlib ``http.server`` only — nothing to install on a TPU VM. OFF by
default: the server starts only when ``obs.metrics_port`` is set, and it
binds 127.0.0.1 unless ``obs.metrics_host`` says otherwise (a training
host should not expose an unauthenticated scrape target to the network;
reach it remotely over an SSH tunnel — docs/TPU_VM_SETUP.md).

``/metrics`` renders the shared registry in Prometheus format 0.0.4;
``/healthz`` answers ``ok`` (livenesss for the supervisor or an external
prober: the HTTP thread answering proves the process is not wedged at
the interpreter level). A health PROVIDER (`set_health_provider`)
upgrades the body to JSON progress facts — `last_step_age_s` from the
trainer, `last_dispatch_age_s` + the live registry `model_version` from
the serving plane — so a probe can tell wedged-but-listening (the HTTP
thread answers while the ages grow without bound) from healthy, without
the run watchdog's deeper diagnosis.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from novel_view_synthesis_3d_tpu.obs.registry import (
    MetricsRegistry,
    get_registry,
)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Background /metrics endpoint over one registry; `close()` to stop.

    `port=0` binds an ephemeral port (tests); the actual port is on
    `.port` either way."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 port: int = 0, host: str = "127.0.0.1"):
        self.registry = registry if registry is not None else get_registry()
        self._health_provider: Optional[Callable[[], dict]] = None
        self._metrics_extra: Optional[Callable[[], str]] = None
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.split("?")[0] == "/metrics":
                    text = outer.registry.render_prometheus()
                    extra = outer._metrics_extra
                    if extra is not None:
                        try:
                            text += extra()
                        except Exception:
                            pass  # aggregation failure ≠ scrape failure
                    body = text.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path.split("?")[0] == "/healthz":
                    body, ctype = b"ok\n", "text/plain"
                    provider = outer._health_provider
                    if provider is not None:
                        try:
                            body = (json.dumps(provider()) + "\n").encode()
                            ctype = "application/json"
                        except Exception:
                            # A broken provider must not take liveness
                            # down with it — fall back to the bare ok.
                            body, ctype = b"ok\n", "text/plain"
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def log_message(self, fmt, *args):
                pass  # scrapes every few seconds must not spam the run log

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="obs-metrics-http")
        self._thread.start()

    def set_health_provider(
            self, provider: Optional[Callable[[], dict]]) -> None:
        """Install (or clear, with None) the /healthz JSON body source —
        a zero-arg callable returning a JSON-serializable dict, called
        per request on the HTTP thread so the ages it reports are live."""
        self._health_provider = provider

    def set_metrics_extra(
            self, extra: Optional[Callable[[], str]]) -> None:
        """Install (or clear) extra Prometheus exposition text appended
        after the local registry's render — the fleet router hangs its
        replica-relabeled aggregation here, making the router's own
        /metrics the single scrape surface for the whole fleet."""
        self._metrics_extra = extra

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


def start_metrics_server(registry: Optional[MetricsRegistry] = None,
                         port: int = 0,
                         host: str = "127.0.0.1") -> MetricsServer:
    return MetricsServer(registry, port, host)
