"""obs: the unified telemetry layer (docs/DESIGN.md "Observability").

Three pillars, one package:

  - tracing (obs.trace): hierarchical spans with Perfetto/Chrome-trace
    export and an on-demand jax.profiler window;
  - metrics (obs.registry + obs.bus + obs.server): a counter/gauge/
    histogram registry with pluggable sinks — the legacy
    metrics.csv/events.csv formats (EventBus is the ONLY writer), a
    JSONL sink, and a Prometheus /metrics endpoint;
  - utilization (obs.devmon): device-memory polling and MFU gauges.

`RunTelemetry.create(cfg.obs, results_folder)` wires all of it for one
run; trainer, serving CLI, and bench each hold one. Everything is
host-side and cheap: no jitted code changes, zero new steady-state
recompiles, and with `obs.metrics_port` unset no socket is ever opened.

This module imports no jax at load time — the supervisor process uses
the bus while deliberately holding no JAX state.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from novel_view_synthesis_3d_tpu.obs.bus import (  # noqa: F401
    EVENTS_HEADER,
    EventBus,
    append_event,
    events_csv_path,
    read_events,
    numerics_path,
)
from novel_view_synthesis_3d_tpu.obs.compiles import (  # noqa: F401
    CompileLedger,
    compiles_path,
    fingerprint_args,
    fingerprint_diff,
    hlo_hash,
    last_recompile,
    load_costmap,
    load_ledger,
    write_costmap,
    xunet_costmap,
)
from novel_view_synthesis_3d_tpu.obs.doctor import (  # noqa: F401
    diagnose_pair,
    diagnose_trajectory,
    load_doctor,
    write_doctor,
)
from novel_view_synthesis_3d_tpu.obs.flight import (  # noqa: F401
    FlightRecorder,
    NullFlightRecorder,
)
from novel_view_synthesis_3d_tpu.obs.numerics import (  # noqa: F401
    NumericsMonitor,
    first_bad_group,
    group_assignment,
    group_labels,
    group_stats,
)
from novel_view_synthesis_3d_tpu.obs.profiler import (  # noqa: F401
    ContinuousProfiler,
    attribute_device_time,
    make_profiler,
    profile_rows,
)
from novel_view_synthesis_3d_tpu.obs.registry import (  # noqa: F401
    MetricsRegistry,
    get_registry,
    reset_registry,
)
from novel_view_synthesis_3d_tpu.obs.roofline import (  # noqa: F401
    roofline_rows,
    top_headroom,
)
from novel_view_synthesis_3d_tpu.obs.runindex import RunIndex  # noqa: F401
from novel_view_synthesis_3d_tpu.obs.server import (  # noqa: F401
    MetricsServer,
    start_metrics_server,
)
from novel_view_synthesis_3d_tpu.obs.trace import (  # noqa: F401
    NullTracer,
    Tracer,
    XProfWindow,
)

TRACE_FILE = "trace.json"


@dataclasses.dataclass
class RunTelemetry:
    """One run's telemetry bundle: tracer + bus + registry (+ device
    monitor, xprof window, metrics endpoint). Create via `create`;
    `finalize()` exports trace.json and stops the background pieces —
    idempotent, safe in finally blocks."""

    tracer: object
    bus: EventBus
    registry: MetricsRegistry
    devmon: Optional[object] = None
    xprof: Optional[XProfWindow] = None
    server: Optional[MetricsServer] = None
    flight: object = None
    results_folder: str = "."
    _finalized: bool = False

    @classmethod
    def create(cls, ocfg, results_folder: str, *,
               registry: Optional[MetricsRegistry] = None,
               start_server: bool = True) -> "RunTelemetry":
        """Wire a run's telemetry from an ObsConfig.

        `start_server=False` suppresses the endpoint even when
        obs.metrics_port is set (the supervisor child vs parent, tests).
        With ocfg.enabled False everything degrades to no-ops: a
        NullTracer, a bus with the JSONL sink off, no monitor/server.
        """
        registry = registry if registry is not None else get_registry()
        max_mb = float(getattr(ocfg, "telemetry_max_mb", 0) or 0)
        bus = EventBus(results_folder,
                       jsonl=ocfg.enabled and ocfg.jsonl,
                       jsonl_max_bytes=int(max_mb * 1024 * 1024))
        # Flight recorder is ALWAYS on (even with obs.enabled=False):
        # its tap sits in front of the bus's jsonl-enabled check, so
        # the last ~512 rows are dumpable at any failure site for the
        # cost of a deque append per row.
        flight = FlightRecorder(results_folder)
        bus.tap = flight.record
        if ocfg.enabled and ocfg.trace:
            # on_complete feeds the bus even with the JSONL sink off:
            # the sink check happens inside the bus, AFTER the flight
            # recorder's tap has seen the row.
            tracer = Tracer(
                max_events=ocfg.trace_max_events,
                registry=registry,
                on_complete=bus.span_record)
        else:
            tracer = NullTracer()
        devmon = None
        if ocfg.enabled and ocfg.device_poll_s > 0:
            from novel_view_synthesis_3d_tpu.obs.devmon import DeviceMonitor

            devmon = DeviceMonitor(
                registry, poll_s=ocfg.device_poll_s,
                jsonl_cb=(bus.gauge_record if ocfg.jsonl else None))
            devmon.start()
        xprof = None
        if ocfg.enabled and tuple(ocfg.xprof_steps) != (0, 0):
            xprof = XProfWindow(os.path.join(results_folder, "xprof"),
                                tuple(ocfg.xprof_steps))
        server = None
        if start_server and ocfg.enabled and ocfg.metrics_port:
            server = start_metrics_server(
                registry, port=ocfg.metrics_port, host=ocfg.metrics_host)
            print(f"obs: serving /metrics and /healthz on "
                  f"{server.url('')} (obs.metrics_port)")
        return cls(tracer=tracer, bus=bus, registry=registry,
                   devmon=devmon, xprof=xprof, server=server,
                   flight=flight, results_folder=results_folder)

    def export_trace(self, path: Optional[str] = None) -> Optional[str]:
        if isinstance(self.tracer, NullTracer):
            return None
        return self.tracer.export_chrome_trace(
            path or os.path.join(self.results_folder, TRACE_FILE))

    def finalize(self, export_trace: bool = True) -> None:
        if self._finalized:
            return
        self._finalized = True
        if self.xprof is not None:
            self.xprof.close()
        if self.devmon is not None:
            # Final sample first: the run's last allocations (and the
            # peak) land in the gauges/JSONL even for sub-period runs.
            try:
                self.devmon.poll()
            except Exception:
                pass
            self.devmon.stop()
        if export_trace:
            try:
                self.export_trace()
            except OSError:
                pass  # telemetry export must never fail the run
        if self.server is not None:
            self.server.close()
            self.server = None
        self.bus.close()
