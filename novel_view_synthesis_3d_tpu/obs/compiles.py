"""Compile ledger + per-op cost map (docs/DESIGN.md "Training numerics
& compile observatory").

Every jit build across train/serve/bench records an entry in
``compiles.jsonl``: a fingerprint (arg shapes/dtypes + a static-config
digest), compile wall time, and an HLO module hash. A rebuild under the
SAME name with a DIFFERENT fingerprint is a recompile: the ledger diffs
against the prior fingerprint and logs WHICH argument changed — the
answer `nvs3d obs compiles --why N` renders and serve_bench's
zero-recompile asserts print on failure. This module is the only place
that names ``compiles.jsonl`` / ``costmap.json`` (the events.csv
conformance convention).

``xunet_costmap`` is the one-time per-op cost model: lower each op of
the op-sliced XUNet (models/xunet.pipeline_op_specs) on abstract shapes
and read XLA's cost_analysis — per-op FLOPs/bytes with NO XLA compile
and no device work, keyed by the same group labels the numerics
observatory uses.

No jax at module load (supervisor constraint); traced helpers import it
lazily.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

_COMPILES_FILE = "compiles.jsonl"
_COSTMAP_FILE = "costmap.json"


def compiles_path(results_folder: str) -> str:
    return os.path.join(results_folder, _COMPILES_FILE)


def costmap_path(results_folder: str) -> str:
    return os.path.join(results_folder, _COSTMAP_FILE)


def static_digest(obj) -> str:
    """Short stable digest of a build's static configuration (anything
    with a deterministic repr — config dataclasses, cache-key tuples)."""
    return hashlib.sha256(repr(obj).encode()).hexdigest()[:12]


def hlo_hash(lowered) -> str:
    """Short hash of a lowered computation's HLO text ("" when the
    lowering cannot render — never fatal, the ledger entry just goes
    unhashed)."""
    try:
        return hashlib.sha256(lowered.as_text().encode()).hexdigest()[:12]
    except Exception:
        return ""


def fingerprint_args(*args, static=None) -> dict:
    """Build a ledger fingerprint from a jit call's arguments.

    {"args": {leaf path: "dtype[shape]"}, "static": digest}. Leaves are
    described by shape/dtype only (values never enter the ledger), so
    two calls fingerprint equal exactly when XLA would reuse the cached
    executable for them.
    """
    import jax

    described: Dict[str, str] = {}
    for i, arg in enumerate(args):
        flat = jax.tree_util.tree_flatten_with_path(arg)[0]
        for path, leaf in flat:
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            desc = (f"{dtype}{list(shape)}"
                    if shape is not None and dtype is not None
                    else repr(leaf)[:64])
            described[f"arg{i}{jax.tree_util.keystr(path)}"] = desc
    fp = {"args": described}
    if static is not None:
        fp["static"] = static_digest(static)
    return fp


def fingerprint_diff(old: dict, new: dict) -> List[str]:
    """Human-readable lines naming what changed between fingerprints —
    the recompile culprit."""
    lines: List[str] = []
    o_args, n_args = old.get("args", {}), new.get("args", {})
    for key in sorted(set(o_args) | set(n_args)):
        if key not in n_args:
            lines.append(f"{key}: {o_args[key]} -> (removed)")
        elif key not in o_args:
            lines.append(f"{key}: (new) -> {n_args[key]}")
        elif o_args[key] != n_args[key]:
            lines.append(f"{key}: {o_args[key]} -> {n_args[key]}")
    if old.get("static", "") != new.get("static", ""):
        lines.append(f"static digest: {old.get('static', '')} -> "
                     f"{new.get('static', '')}")
    return lines


class CompileLedger:
    """Append-only record of jit builds for one results folder.

    Thread-safe (the serving plane builds programs from worker threads).
    `record` returns the entry it wrote; a recompile entry carries
    `diff` (the fingerprint delta) and `changed` (the first diff line —
    the one-line culprit)."""

    def __init__(self, results_folder: str, registry=None):
        self.results_folder = results_folder
        self._lock = threading.Lock()
        self._by_name: Dict[str, dict] = {}
        self.entries: List[dict] = []
        self._counter = (registry.counter(
            "nvs3d_compiles_total",
            "jit builds recorded in the compile ledger")
            if registry is not None else None)

    def record(self, name: str, fingerprint: dict, *,
               wall_s: Optional[float] = None, hlo: str = "",
               backend: str = "") -> dict:
        entry = {"kind": "compile", "name": name, "t": round(time.time(), 3),
                 "fingerprint": fingerprint}
        if wall_s is not None:
            entry["wall_s"] = round(float(wall_s), 3)
        if hlo:
            entry["hlo_hash"] = hlo
        if backend:
            entry["backend"] = backend
        with self._lock:
            prev = self._by_name.get(name)
            if prev is not None and prev != fingerprint:
                entry["kind"] = "recompile"
                diff = fingerprint_diff(prev, fingerprint)
                entry["diff"] = diff
                entry["changed"] = diff[0] if diff else "(fingerprint " \
                    "changed but no field-level diff — same shapes, new " \
                    "static digest?)"
            self._by_name[name] = fingerprint
            self.entries.append(entry)
        if self._counter is not None:
            self._counter.inc(name=name, kind=entry["kind"])
        self._append(entry)
        return entry

    def recompiles(self) -> List[dict]:
        with self._lock:
            return [e for e in self.entries if e["kind"] == "recompile"]

    def _append(self, entry: dict) -> None:
        # Open per record: builds are rare by construction — no handle
        # to leak across supervisor generations (the append_event policy).
        try:
            os.makedirs(self.results_folder, exist_ok=True)
            with open(compiles_path(self.results_folder), "a") as fh:
                fh.write(json.dumps(entry) + "\n")
                fh.flush()
        except (OSError, TypeError, ValueError):
            pass  # ledger IO faults are never the run's fault


def load_ledger(results_folder: str) -> List[dict]:
    """Read compiles.jsonl back ([] when absent/empty); skips torn
    trailing lines the way every jsonl consumer here does."""
    path = compiles_path(results_folder)
    if not os.path.exists(path):
        return []
    entries = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                entries.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return entries


def last_recompile(results_folder: str) -> Optional[dict]:
    """Newest recompile entry on disk — what a zero-recompile assert
    prints as the culprit. None when the ledger records no recompile."""
    found = None
    for entry in load_ledger(results_folder):
        if entry.get("kind") == "recompile":
            found = entry
    return found


# ---------------------------------------------------------------------
# Per-op cost map
# ---------------------------------------------------------------------
def xunet_costmap(config, model_batch) -> List[dict]:
    """One-time per-op FLOPs/bytes table over the op-sliced XUNet.

    `model_batch` supplies SHAPES only (the trainer's _sample_model_batch
    projection of any train batch). Each op is lowered in isolation —
    ops=(i, i+1) with the carry threaded through jax.eval_shape — and
    costed with XLA's lowered cost_analysis: a trace per op, no XLA
    compile, no device execution. Rows carry the numerics group label so
    a sentry trip and a grad-norm spike name ops the same way.
    """
    import jax

    from novel_view_synthesis_3d_tpu.models.xunet import (
        XUNet, op_groups, pipeline_op_specs)

    model = XUNet(config.model)
    specs = pipeline_op_specs(config.model)
    labels = [label for label, _ in op_groups(config.model)]

    def struct(tree):
        return jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)

    import numpy as np

    batch_s = struct(model_batch)
    B = model_batch["z"].shape[0]
    mask_s = jax.ShapeDtypeStruct((B,), np.float32)
    # Wrapped so `train` stays a Python constant (eval_shape would trace
    # a bare keyword into an abstract bool and break flax's branching).
    params_s = struct(jax.eval_shape(
        lambda b, m: model.init(jax.random.PRNGKey(0), b,
                                cond_mask=m, train=False),
        batch_s, mask_s))

    rows: List[dict] = []
    carry_s = None
    for i, (kind, info) in enumerate(specs):
        def op_fwd(variables, batch, cond_mask, carry, _i=i):
            return model.apply(variables, batch, cond_mask=cond_mask,
                               train=False, ops=(_i, _i + 1), carry=carry)

        lowered = jax.jit(op_fwd).lower(params_s, batch_s, mask_s, carry_s)
        ca = lowered.cost_analysis()
        # Return shape varies across JAX versions (list → dict); the
        # legacy list is a refusal, not a compat path (bench._cost_numbers
        # has the full rationale).
        if isinstance(ca, dict):
            flops = float(ca.get("flops", 0.0)) or None
            byts = float(ca.get("bytes accessed", 0.0)) or None
        else:
            flops, byts = None, None
        rows.append({"op": i, "kind": kind,
                     "name": info.get("name", kind),
                     "group": labels[i], "flops": flops, "bytes": byts})
        if i + 1 < len(specs):
            carry_s = jax.eval_shape(op_fwd, params_s, batch_s, mask_s,
                                     carry_s)
    return rows


def write_costmap(results_folder: str, rows: Sequence[dict]) -> str:
    """Persist the cost map next to the run's other telemetry; returns
    the path. Kept here so producers (bench) never name the file."""
    os.makedirs(results_folder, exist_ok=True)
    path = costmap_path(results_folder)
    with open(path, "w") as fh:
        json.dump({"ops": list(rows)}, fh, indent=2)
        fh.write("\n")
    return path


def load_costmap(results_folder: str) -> List[dict]:
    path = costmap_path(results_folder)
    if not os.path.exists(path):
        return []
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return []
    return list(doc.get("ops", []))
