"""Always-on flight recorder: a bounded in-memory ring of the most
recent telemetry rows, dumped to disk when something goes wrong.

The PR 2 stall bundles answered "what was the process doing when the
watchdog fired"; this answers the more common postmortem question:
"what happened in the seconds BEFORE the anomaly/restart/drain
timeout" — the spans, events, and gauge samples that already flow
through the EventBus, retained even when `obs.jsonl` is off (the tap
sits in front of the enabled check) and even when the full
telemetry.jsonl has long since rotated aside.

Design constraints, in order:

  - Recording must be cheap enough to leave on in production serving:
    one lock + deque append per row, no serialization until dump time.
  - A dump must never take down the run it is diagnosing: every
    public method swallows its own faults; the dump is written to a
    temp file and atomically renamed, so a crash mid-dump leaves no
    truncated JSON for the postmortem tooling to choke on.
  - Dumps are individually numbered (``flight_<reason>_<n>.json``)
    rather than overwritten: a restart loop that dumps five times
    leaves five files, and the ordering IS the story.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

DEFAULT_CAPACITY = 512


class FlightRecorder:
    """Thread-safe bounded ring of recent telemetry entries.

    Wire into a bus with ``bus.tap = recorder.record`` (the EventBus
    calls its tap before — and regardless of — the JSONL enabled
    check), or feed it directly via `record` / `note`.
    """

    def __init__(self, results_folder: str,
                 capacity: int = DEFAULT_CAPACITY):
        self.results_folder = results_folder
        self._lock = threading.Lock()
        import collections

        self._ring: "collections.deque" = collections.deque(
            maxlen=max(8, int(capacity)))
        self._n_recorded = 0
        self._n_dumped = 0
        self.dumps: List[str] = []

    # -- recording -----------------------------------------------------
    def record(self, entry: dict) -> None:
        """Retain one telemetry row (shallow-copied, wall-stamped)."""
        try:
            row = dict(entry)
            row.setdefault("t", round(time.time(), 3))
            with self._lock:
                self._ring.append(row)
                self._n_recorded += 1
        except Exception:
            pass  # the recorder must never become the run's fault

    def note(self, kind: str, **fields) -> None:
        """Record an entry authored by the recorder's owner (e.g. the
        service's event mirror) rather than tapped off the bus."""
        self.record({"kind": kind, **fields})

    def entries(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    # -- dumping -------------------------------------------------------
    def dump(self, reason: str, **context) -> Optional[str]:
        """Atomically write the ring as ``flight_<reason>_<n>.json``
        under the results folder; returns the path (None on failure —
        a forensics miss, never a crash). The newest entries sit at the
        END of ``entries``, so the triggering event is the tail."""
        reason = "".join(
            c if (c.isalnum() or c in "._-") else "_" for c in reason
        ) or "unknown"
        try:
            with self._lock:
                entries = list(self._ring)
                n = self._n_dumped
                self._n_dumped += 1
                recorded = self._n_recorded
            os.makedirs(self.results_folder, exist_ok=True)
            path = os.path.join(self.results_folder,
                                f"flight_{reason}_{n}.json")
            doc = {
                "reason": reason,
                "dumped_at": round(time.time(), 3),
                "n_recorded_total": recorded,
                "n_entries": len(entries),
                "context": {k: v for k, v in context.items()
                            if isinstance(v, (int, float, str, bool))},
                "entries": entries,
            }
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(doc, fh)
            os.replace(tmp, path)
            with self._lock:
                self.dumps.append(path)
            return path
        except Exception:
            return None


class NullFlightRecorder:
    """Disabled recorder with the same surface (keeps call sites free
    of None checks when no results folder exists to dump into)."""

    dumps: List[str] = []

    def record(self, entry: dict) -> None:
        pass

    def note(self, kind: str, **fields) -> None:
        pass

    def entries(self) -> List[dict]:
        return []

    def dump(self, reason: str, **context) -> Optional[str]:
        return None
