"""Raytraced multi-view SRN-format dataset: geometrically REAL scenes.

The blob fixture (data/synthetic.py) paints a pose-dependent pattern but is
not a consistent 3-D scene — a model can fit it without learning geometry,
so PSNR on held-out views says nothing about novel-view synthesis. This
module renders actual 3-D scenes (colored spheres on a ground plane,
lambertian shading) through the SAME pinhole camera model the framework
uses everywhere (models/rays.py: pixel centers at +0.5, K = [[f,0,cx],
[0,f,cy],[0,0,1]], cam→world (R, t)), so:

  - every view of an instance is a true projection of one underlying scene;
  - cross-view consistency is exactly what a novel-view model must learn;
  - eval PSNR/SSIM on held-out poses measures real view synthesis.

This is the in-environment stand-in for SRN ShapeNet cars (no network
egress to fetch the real dump — BASELINE.md); the directory layout, pose
files, and intrinsics match the SRN format byte-for-byte so the identical
pipeline consumes either.
"""

from __future__ import annotations

import os

import numpy as np
from PIL import Image

from novel_view_synthesis_3d_tpu.data.synthetic import look_at_pose

_LIGHT_DIR = np.array([0.4, 0.25, 0.88])
_LIGHT_DIR = _LIGHT_DIR / np.linalg.norm(_LIGHT_DIR)


def random_scene(rng: np.random.Generator, num_spheres: int = 4) -> dict:
    """A random scene: spheres clustered near the origin + a ground plane."""
    centers = np.stack([
        rng.uniform(-0.7, 0.7, num_spheres),
        rng.uniform(-0.7, 0.7, num_spheres),
        rng.uniform(0.0, 0.8, num_spheres),
    ], axis=1)
    radii = rng.uniform(0.18, 0.45, num_spheres)
    colors = rng.uniform(0.15, 0.95, (num_spheres, 3))
    return {
        "centers": centers.astype(np.float32),
        "radii": radii.astype(np.float32),
        "colors": colors.astype(np.float32),
        "ground_color": rng.uniform(0.3, 0.8, 3).astype(np.float32),
        "ground_z": np.float32(-0.5),
    }


def render_scene(scene: dict, pose: np.ndarray, K: np.ndarray,
                 size: int) -> np.ndarray:
    """Raytrace one view. pose: cam→world 4×4; returns uint8 (S, S, 3)."""
    R, t = pose[:3, :3], pose[:3, 3]
    v, u = np.mgrid[0:size, 0:size].astype(np.float64) + 0.5
    x = (u - K[0, 2]) / K[0, 0]
    y = (v - K[1, 2]) / K[1, 1]
    d_cam = np.stack([x, y, np.ones_like(x)], axis=-1)
    d = d_cam @ R.T
    d = d / np.linalg.norm(d, axis=-1, keepdims=True)   # (S, S, 3)
    o = t[None, None, :]

    t_hit = np.full((size, size), np.inf)
    color = np.ones((size, size, 3))                    # white background
    normal = np.zeros((size, size, 3))

    # Spheres: solve |o + s·d − c|² = r².
    for c, r, col in zip(scene["centers"], scene["radii"], scene["colors"]):
        oc = o - c[None, None, :]
        b = np.sum(oc * d, axis=-1)
        q = np.sum(oc * oc, axis=-1) - r * r
        disc = b * b - q
        hit = disc >= 0
        s = -b - np.sqrt(np.where(hit, disc, 0.0))
        hit &= (s > 1e-6) & (s < t_hit)
        t_hit = np.where(hit, s, t_hit)
        p = o + s[..., None] * d
        n = (p - c[None, None, :]) / r
        color = np.where(hit[..., None], col[None, None, :], color)
        normal = np.where(hit[..., None], n, normal)

    # Ground plane z = ground_z (only where no nearer sphere).
    gz = float(scene["ground_z"])
    denom = d[..., 2]
    s_g = np.where(np.abs(denom) > 1e-9, (gz - o[..., 2]) / denom, np.inf)
    p_g = o + s_g[..., None] * d
    in_disk = (p_g[..., 0] ** 2 + p_g[..., 1] ** 2) < 4.0
    hit_g = (s_g > 1e-6) & (s_g < t_hit) & in_disk
    # Checker pattern so the plane carries pose-sensitive texture.
    checker = ((np.floor(p_g[..., 0] * 2) + np.floor(p_g[..., 1] * 2)) % 2)
    g_col = scene["ground_color"][None, None, :] * (0.6 + 0.4 * checker[..., None])
    t_hit = np.where(hit_g, s_g, t_hit)
    color = np.where(hit_g[..., None], g_col, color)
    normal = np.where(hit_g[..., None],
                      np.array([0.0, 0.0, 1.0])[None, None, :], normal)

    # Lambertian shading with a fixed ambient floor; background stays white.
    lam = np.clip(np.sum(normal * _LIGHT_DIR[None, None, :], axis=-1), 0, 1)
    shaded = color * (0.35 + 0.65 * lam[..., None])
    out = np.where(np.isfinite(t_hit)[..., None], shaded, color)
    return (np.clip(out, 0, 1) * 255).astype(np.uint8)


def write_raytraced_srn(root: str, num_instances: int = 8,
                        views_per_instance: int = 24, image_size: int = 64,
                        focal: float | None = None, seed: int = 0) -> str:
    """Create an SRN directory tree of raytraced scenes.

    Cameras orbit each scene at jittered azimuth/elevation/distance (views
    cover the sphere the way SRN's cars trainset does), written in the same
    layout as data/synthetic.py: root/inst_XX/{rgb,pose,intrinsics.txt}.
    """
    rng = np.random.default_rng(seed)
    focal = focal if focal is not None else image_size * 1.2
    K = np.array([[focal, 0, image_size / 2],
                  [0, focal, image_size / 2],
                  [0, 0, 1]], dtype=np.float64)
    for i in range(num_instances):
        inst = os.path.join(root, f"inst_{i:02d}")
        os.makedirs(os.path.join(inst, "rgb"), exist_ok=True)
        os.makedirs(os.path.join(inst, "pose"), exist_ok=True)
        scene = random_scene(rng)
        with open(os.path.join(inst, "intrinsics.txt"), "w") as fh:
            fh.write(f"{focal} {image_size / 2} {image_size / 2} 0.\n")
            fh.write("0. 0. 0.\n")
            fh.write("1.\n")
            fh.write(f"{image_size} {image_size}\n")
        for v in range(views_per_instance):
            az = 2 * np.pi * (v + rng.uniform(-0.3, 0.3)) / views_per_instance
            el = rng.uniform(0.15, 0.7)
            dist = rng.uniform(2.2, 3.0)
            cam = np.array([
                dist * np.cos(az) * np.cos(el),
                dist * np.sin(az) * np.cos(el),
                dist * np.sin(el),
            ])
            pose = look_at_pose(cam)
            img = render_scene(scene, pose.astype(np.float64), K, image_size)
            Image.fromarray(img).save(
                os.path.join(inst, "rgb", f"{v:06d}.png"))
            np.savetxt(os.path.join(inst, "pose", f"{v:06d}.txt"),
                       pose, fmt="%.8f")
    return root
