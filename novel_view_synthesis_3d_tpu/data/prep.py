"""Offline dataset preparation utilities.

Capability-parity with the reference's offline prep helpers
(`/root/reference/dataset/data_util.py:75-142`): the SRN per-object
train/val splitter and the ShapeNet CSV-driven train/val/test copier.
Differences: stdlib `csv` instead of pandas, symlink option to avoid
duplicating large datasets, and returned manifests for testability.
"""

from __future__ import annotations

import csv
import os
import shutil
from glob import glob
from typing import Dict, List, Tuple


def _place(src: str, dst: str, symlink: bool) -> None:
    os.makedirs(os.path.dirname(dst), exist_ok=True)
    if symlink:
        if os.path.lexists(dst):
            os.remove(dst)
        os.symlink(os.path.abspath(src), dst)
    else:
        shutil.copy(src, dst)


def train_val_split(object_dir: str, train_dir: str, val_dir: str,
                    *, symlink: bool = False,
                    invert: bool = False) -> Tuple[int, int]:
    """Split one SRN object dir into train/val by 1-in-3 round-robin.

    Reference semantics (data_util.py:75-98): every item with index % 3 == 0
    goes to train, the rest to val (a 1:2 split — the reference TRAINS on
    the sparse third); outputs are renumbered %06d within each split;
    intrinsics.txt is copied to both. Handles the pose/rgb/depth subdirs,
    tolerating a missing depth/ (many SRN dumps omit it). Returns
    (num_train_views, num_val_views).

    `invert=True` flips the assignment (train on the 2-in-3 slice, hold
    out 1-in-3) — the conventional dense-train/sparse-holdout protocol the
    quality runs use; the default stays reference-faithful.
    """
    subdirs = [("pose", "*.txt", ".txt"), ("rgb", "*.png", ".png"),
               ("depth", "*.png", ".png")]
    n_train = n_val = 0
    for split_dir in (train_dir, val_dir):
        os.makedirs(split_dir, exist_ok=True)
        _place(os.path.join(object_dir, "intrinsics.txt"),
               os.path.join(split_dir, "intrinsics.txt"), symlink)

    for name, pattern, ending in subdirs:
        items = sorted(glob(os.path.join(object_dir, name, pattern)))
        if not items and name == "depth":
            continue
        train_counter = val_counter = 0
        for i, item in enumerate(items):
            if (i % 3 == 0) != invert:
                dst = os.path.join(train_dir, name,
                                   f"{train_counter:06d}{ending}")
                train_counter += 1
            else:
                dst = os.path.join(val_dir, name, f"{val_counter:06d}{ending}")
                val_counter += 1
            _place(item, dst, symlink)
        if name == "rgb":
            n_train, n_val = train_counter, val_counter
    return n_train, n_val


def read_split_csv(csv_path: str, synset_id: str) -> Dict[str, List[str]]:
    """ShapeNet split CSV → {'train'|'val'|'test': [modelId, ...]}.

    Expects the official ShapeNet all.csv columns (id, synsetId, subSynsetId,
    modelId, split).
    """
    out: Dict[str, List[str]] = {"train": [], "val": [], "test": []}
    target = int(synset_id)
    with open(csv_path, newline="") as fh:
        for row in csv.DictReader(fh):
            try:
                # int-compare both sides: ShapeNet CSVs zero-pad synset IDs.
                if int(row["synsetId"]) != target:
                    continue
            except (TypeError, ValueError):
                continue
            split = row["split"]
            if split in out:
                out[split].append(str(row["modelId"]))
    return out


def shapenet_train_test_split(shapenet_path: str, synset_id: str, name: str,
                              csv_path: str, *, symlink: bool = False,
                              verbose: bool = True) -> Dict[str, List[str]]:
    """Copy ShapeNet instances into <synset>_<name>_{train,val,test} dirs per
    the split CSV (reference data_util.py:115-142). Missing instance dirs are
    skipped with a note. Returns the modelIds actually placed per split."""
    splits = read_split_csv(csv_path, synset_id)
    if verbose:
        print(len(splits["train"]), len(splits["val"]), len(splits["test"]))
    placed: Dict[str, List[str]] = {k: [] for k in splits}
    for split, model_ids in splits.items():
        trgt = os.path.join(shapenet_path, f"{synset_id}_{name}_{split}")
        os.makedirs(trgt, exist_ok=True)
        for model_id in model_ids:
            src = os.path.join(shapenet_path, str(synset_id), model_id)
            dst = os.path.join(trgt, model_id)
            if not os.path.isdir(src):
                if verbose:
                    print(f"{model_id} does not exist")
                continue
            if symlink:
                if os.path.lexists(dst):
                    os.remove(dst)
                os.symlink(os.path.abspath(src), dst)
            else:
                shutil.copytree(src, dst, dirs_exist_ok=True)
            placed[split].append(model_id)
    return placed
