"""SRN ShapeNet dataset format: directory layout, intrinsics, poses, images.

Format (reference dataset/data_loader.py:27-65, data_util.py:12-52,
util.py:46-81):

  root/<instance>/rgb/*.png|jpg      images (any size, square-cropped)
  root/<instance>/pose/*.txt         4×4 cam→world pose, either 4 lines of 4
                                     floats or one line of 16 floats
  root/<instance>/intrinsics.txt     line 1: f cx cy _
                                     line 2: grid barycenter (3 floats)
                                     line 3: scale
                                     line 4: height width
                                     line 5 (optional): world2cam flag (int)

Key deviations from the reference (deliberate, SURVEY.md §7 ledger):
  - intrinsics are parsed ONCE per instance and cached (the reference
    re-reads + re-parses intrinsics.txt on EVERY __getitem__,
    data_loader.py:81-83);
  - images are returned HWC float32 in [-1, 1] (TPU NHWC layout; the
    reference round-trips through CHW);
  - NO noising here: the pipeline emits clean pairs, forward diffusion runs
    on device inside the train step.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from glob import glob
from typing import List, Optional, Sequence, Tuple

import numpy as np

from novel_view_synthesis_3d_tpu.utils import faultinject

try:  # cv2 gives exact INTER_AREA parity with the reference resize
    import cv2

    _HAS_CV2 = True
except Exception:  # pragma: no cover
    _HAS_CV2 = False

from PIL import Image

IMG_EXTENSIONS = (".png", ".jpg", ".jpeg", ".JPG", ".JPEG", ".PNG")


def glob_images(directory: str) -> List[str]:
    paths: List[str] = []
    for ext in ("*.png", "*.jpg", "*.jpeg", "*.JPG", "*.JPEG", "*.PNG"):
        paths.extend(glob(os.path.join(directory, ext)))
    return sorted(set(paths))


def parse_intrinsics_text(text: str,
                          trgt_sidelength: Optional[int] = None):
    """Parse SRN intrinsics.txt CONTENT — the packed-record backend stores
    the raw text in its index and parses at read time, so the sidelength
    rescale below stays a read-time decision and both backends share one
    implementation (bit-identical K for any sidelength)."""
    import io

    fh = io.StringIO(text)
    f, cx, cy, _ = map(float, fh.readline().split())
    barycenter = np.array(list(map(float, fh.readline().split())),
                          dtype=np.float32)
    scale = float(fh.readline())
    height, width = map(float, fh.readline().split())
    line5 = fh.readline().strip()
    try:
        world2cam = bool(int(line5))
    except ValueError:
        world2cam = False

    if trgt_sidelength is not None:
        cx = cx / width * trgt_sidelength
        cy = cy / height * trgt_sidelength
        f = trgt_sidelength / height * f

    K = np.array([[f, 0.0, cx], [0.0, f, cy], [0.0, 0.0, 1.0]],
                 dtype=np.float32)
    return K, barycenter, scale, world2cam


def parse_intrinsics(filepath: str, trgt_sidelength: Optional[int] = None):
    """Parse SRN intrinsics.txt → (K 3×3 f32, barycenter, scale, world2cam).

    Focal length and principal point are rescaled to the target sidelength:
    cx·S/W, cy·S/H, f·S/H (reference util.py:64-67).
    """
    with open(filepath, "r") as fh:
        return parse_intrinsics_text(fh.read(),
                                     trgt_sidelength=trgt_sidelength)


def load_pose(filename: str) -> np.ndarray:
    """4×4 cam→world pose from txt: 4 rows of 4, or one flat row of 16."""
    with open(filename) as fh:
        lines = fh.read().splitlines()
    vals = [v for line in lines for v in line.split()]
    if len(vals) < 16:
        raise ValueError(f"pose file {filename} has {len(vals)} values, need 16")
    return np.asarray(vals[:16], dtype=np.float32).reshape(4, 4)


def square_center_crop(img: np.ndarray) -> np.ndarray:
    h, w = img.shape[:2]
    m = min(h, w)
    ch, cw = h // 2, w // 2
    return img[ch - m // 2: ch + m // 2, cw - m // 2: cw + m // 2]


def decode_rgb(source, sidelength: Optional[int] = None) -> np.ndarray:
    """Image (path OR file-like, e.g. BytesIO over packed-shard bytes) →
    HWC float32 in [-1, 1]: decode, drop alpha, square-crop, INTER_AREA
    resize (reference data_util.py:12-24 semantics). One implementation
    for the file-walking and packed backends — the bit-identity contract
    between them rests on sharing this exact decode chain."""
    img = np.asarray(Image.open(source).convert("RGB"),
                     dtype=np.float32) / 255.0
    img = square_center_crop(img)
    if sidelength is not None and img.shape[0] != sidelength:
        if _HAS_CV2:
            img = cv2.resize(img, (sidelength, sidelength),
                             interpolation=cv2.INTER_AREA)
        else:  # PIL BOX filter ≈ area averaging
            pil = Image.fromarray((img * 255).astype(np.uint8))
            pil = pil.resize((sidelength, sidelength), Image.BOX)
            img = np.asarray(pil, dtype=np.float32) / 255.0
    return (img - 0.5) * 2.0


def load_rgb(path: str, sidelength: Optional[int] = None) -> np.ndarray:
    """Image file → HWC float32 in [-1, 1] (see decode_rgb)."""
    return decode_rgb(path, sidelength)


def load_depth(path: str, sidelength: Optional[int] = None) -> np.ndarray:
    """SRN depth map → (H, W, 1) float32 in meters.

    Reference semantics (data_util.py:27-41): raw 16-bit PNG values × 1e-4,
    nearest-neighbor resize (depth must not be averaged across edges). Layout
    is HWC (TPU NHWC) instead of the reference's CHW.
    """
    raw = np.asarray(Image.open(path))
    depth = raw.astype(np.float32)
    if depth.ndim == 3:
        depth = depth[:, :, 0]
    if sidelength is not None and depth.shape[:2] != (sidelength, sidelength):
        if _HAS_CV2:
            depth = cv2.resize(depth, (sidelength, sidelength),
                               interpolation=cv2.INTER_NEAREST)
        else:
            pil = Image.fromarray(depth)
            depth = np.asarray(
                pil.resize((sidelength, sidelength), Image.NEAREST),
                dtype=np.float32)
    return (depth * 1e-4)[:, :, None]


def load_params(path: str) -> np.ndarray:
    """First line of a params.txt as a float32 vector (data_util.py:55-59)."""
    with open(path) as fh:
        first = fh.readline()
    return np.array([float(v) for v in first.split()], dtype=np.float32)


@dataclasses.dataclass
class SRNInstance:
    """One object instance; intrinsics parsed once and cached."""

    instance_idx: int
    instance_dir: str
    color_paths: List[str]
    pose_paths: List[str]
    K: np.ndarray  # (3, 3) rescaled to the dataset sidelength
    img_sidelength: int

    def __len__(self) -> int:
        return len(self.pose_paths)

    def view(self, idx: int) -> Tuple[np.ndarray, np.ndarray]:
        """(image HWC [-1,1], pose 4×4) for one observation."""
        rgb = load_rgb(self.color_paths[idx], self.img_sidelength)
        pose = load_pose(self.pose_paths[idx])
        return rgb, pose


def _subset(paths: List[str],
            specific: Optional[Sequence[int]],
            max_n: int) -> List[str]:
    if specific is not None:
        return [paths[i] for i in specific]
    if max_n != -1 and len(paths) > 0:
        idcs = np.linspace(0, len(paths), num=min(max_n, len(paths)),
                           endpoint=False, dtype=int)
        return [paths[i] for i in idcs]
    return paths


class FlatViewDataset:
    """Flat (instance, view) indexing, pair/group sampling, and fault
    quarantine — the backend-independent half of the data plane.

    Subclasses (SRNDataset walking files, records.PackedDataset reading
    sharded records) populate `self.instances` with objects exposing
    `__len__()`, `view(idx) -> (rgb HWC [-1,1], pose 4×4)`, `.K`, and
    `.instance_dir`, then call `_finalize_index()`. Everything above that
    surface — the cumulative-views offsets array with binary-search
    `locate`, the rng-draw order of `pair`/`samples`, and the
    quarantine-and-redraw ladder — is ONE shared implementation, which is
    what makes `backend='packed'` batches bit-identical to
    `backend='files'` for the same (seed, epoch, index).

    `pair`/`samples` are split into a PLAN phase (consumes the rng,
    touches no IO) and an ASSEMBLE phase (decodes the planned views,
    consumes no rng): the compute-overlapped loader
    (pipeline.PipelinedLoader) plans sequentially on the coordinator
    thread and decodes on a worker pool without perturbing the random
    stream."""

    def __init__(self, samples_per_instance: int = 1,
                 max_record_retries: int = 3):
        if samples_per_instance < 1:
            raise ValueError(
                f"samples_per_instance must be >= 1, got {samples_per_instance}")
        self.samples_per_instance = samples_per_instance
        # Data fault tolerance (safe_pair/safe_samples): records whose
        # image/pose failed to load, skipped for the rest of the run.
        # Per-process state — Grain workers each hold their own copy, so a
        # bad record is re-discovered (and re-reported) once per worker.
        self.max_record_retries = max_record_retries
        self.quarantined: set = set()
        self.fault_reports: List[dict] = []
        self.instances: List = []
        self.root_dir = ""

    def _finalize_index(self) -> None:
        """Cumulative-views array over self.instances: one O(num_instances)
        pass at init, then every locate() is a binary search."""
        self._sizes = np.array([len(i) for i in self.instances])
        self._offsets = np.concatenate([[0], np.cumsum(self._sizes)])

    def __len__(self) -> int:
        return int(self._offsets[-1])

    @property
    def num_instances(self) -> int:
        return len(self.instances)

    def locate(self, flat_idx: int) -> Tuple[int, int]:
        """flat index → (instance_idx, view_idx) via binary search over the
        precomputed cumulative-views array (the reference does a linear
        scan over instances per item, data_loader.py:153-161 — O(N) per
        fetch, ruinous at production instance counts)."""
        obj = int(np.searchsorted(self._offsets, flat_idx, side="right") - 1)
        return obj, int(flat_idx - self._offsets[obj])

    def live_indices(self) -> np.ndarray:
        """Flat indices NOT quarantined (the pipelined loader's sample
        space — with nothing quarantined this is arange(len))."""
        if not self.quarantined:
            return np.arange(len(self), dtype=np.int64)
        return np.array([i for i in range(len(self))
                         if i not in self.quarantined], dtype=np.int64)

    # -- plan phase (rng only, no IO) ----------------------------------
    def _plan_pair(self, flat_idx: int, rng: np.random.Generator,
                   num_cond: int = 1) -> tuple:
        """Consume exactly `pair`'s rng draws and return the decode plan
        (obj, target_view, cond_views). Decoding consumes no randomness,
        so plan-then-assemble is bit-identical to the inline path."""
        faultinject.maybe_raise_record(int(flat_idx))
        obj, view = self.locate(flat_idx)
        view2 = self._draw_view(obj, rng)
        cond_views = [view] + [self._draw_view(obj, rng)
                               for _ in range(num_cond - 1)]
        return (obj, view2, cond_views)

    def _plan_samples(self, flat_idx: int, rng: np.random.Generator,
                      num_cond: int = 1) -> List[tuple]:
        """Plan-phase twin of `samples` — same rng call order (pair draws,
        then each sibling's index draw followed by its pair draws)."""
        plans = [self._plan_pair(flat_idx, rng, num_cond=num_cond)]
        obj, _ = self.locate(flat_idx)
        base = int(self._offsets[obj])
        for _ in range(self.samples_per_instance - 1):
            v = int(rng.integers(len(self.instances[obj])))
            plans.append(self._plan_pair(base + v, rng, num_cond=num_cond))
        return plans

    # -- assemble phase (IO only, no rng) ------------------------------
    def _assemble_pair(self, plan: tuple) -> dict:
        obj, view2, cond_views = plan
        inst = self.instances[obj]
        target, pose2 = inst.view(view2)
        xs, R1s, t1s = [], [], []
        for v in cond_views:
            x, pose1 = inst.view(v)
            xs.append(x.astype(np.float32))
            R1s.append(pose1[:3, :3])
            t1s.append(pose1[:3, 3])
        if len(cond_views) == 1:
            x_out, R1_out, t1_out = xs[0], R1s[0], t1s[0]
        else:
            x_out = np.stack(xs)
            R1_out = np.stack(R1s)
            t1_out = np.stack(t1s)
        return {
            "x": x_out,
            "target": target.astype(np.float32),
            "R1": R1_out,
            "t1": t1_out,
            "R2": pose2[:3, :3],
            "t2": pose2[:3, 3],
            "K": inst.K,
        }

    def pair(self, flat_idx: int, rng: np.random.Generator,
             num_cond: int = 1) -> dict:
        """One training record: clean cond view(s) + a random clean target
        view of the same instance, with poses + intrinsics.

        num_cond=1 matches the reference's per-item semantics
        (data_loader.py:80-113: item idx = conditioning view, uniformly
        random second view = target) minus the CPU-side noising, which lives
        on device now. num_cond>1 (3DiM k>1 training) keeps the indexed view
        as the first conditioning frame and draws the rest uniformly; frames
        are stacked on a leading axis (x (Fc,H,W,3), R1 (Fc,3,3), t1 (Fc,3)).
        """
        return self._assemble_pair(
            self._plan_pair(flat_idx, rng, num_cond=num_cond))

    def samples(self, flat_idx: int, rng: np.random.Generator,
                num_cond: int = 1) -> List[dict]:
        """`samples_per_instance` records from flat_idx's instance.

        Reference semantics (data_loader.py:183-195): the indexed
        observation first, then samples_per_instance−1 observations at
        uniformly random view indices of the SAME instance — the torch
        collate then flattens them into the batch. Callers stack the list
        into consecutive batch slots (pipeline.iter_batches)."""
        records = [self.pair(flat_idx, rng, num_cond=num_cond)]
        obj, _ = self.locate(flat_idx)
        base = int(self._offsets[obj])
        for _ in range(self.samples_per_instance - 1):
            v = int(rng.integers(len(self.instances[obj])))
            records.append(self.pair(base + v, rng, num_cond=num_cond))
        return records

    # ------------------------------------------------------------------
    # Data fault tolerance (docs/DESIGN.md "Fault tolerance"): one corrupt
    # image/pose must cost one record, not the run. The safe_* variants
    # quarantine a failing record (skipped for the rest of the run,
    # reported to stderr + fault_reports) and redraw a substitute, bounded
    # by max_record_retries consecutive redraws. The pipeline backends all
    # route through these (pipeline.iter_batches, the Grain transforms; the
    # native loader quarantines by path in native_io).
    # ------------------------------------------------------------------
    def _draw_view(self, obj: int, rng: np.random.Generator) -> int:
        """Uniform random view index of instance `obj`, avoiding
        quarantined views. The first draw is the plain rng.integers call —
        with nothing quarantined the random stream is bit-identical to the
        pre-fault-tolerance one (resume/parity reproducibility)."""
        inst = self.instances[obj]
        v = int(rng.integers(len(inst)))
        if not self.quarantined:
            return v
        base = int(self._offsets[obj])
        if (base + v) not in self.quarantined:
            return v
        allowed = [w for w in range(len(inst))
                   if (base + w) not in self.quarantined]
        if not allowed:
            raise RuntimeError(
                f"data: every view of instance {inst.instance_dir!r} is "
                "quarantined — nothing left to draw")
        return int(allowed[int(rng.integers(len(allowed)))])

    def _locate_failing_record(self, msg: str) -> Optional[int]:
        """Flat index of the record an error message names, or None.
        Backend-specific (the file walker matches paths, the packed reader
        tags its exceptions with .flat_index instead)."""
        del msg
        return None

    def _quarantine(self, flat_idx: int, exc: Exception) -> None:
        self.quarantined.add(int(flat_idx))
        obj, view = self.locate(flat_idx)
        report = {
            "record": int(flat_idx),
            "instance": os.path.basename(
                os.path.normpath(self.instances[obj].instance_dir)),
            "view": view,
            "error": f"{type(exc).__name__}: {exc}",
        }
        self.fault_reports.append(report)
        print(f"warning: data fault: record {flat_idx} "
              f"({report['instance']} view {view}) quarantined: "
              f"{report['error']}", file=sys.stderr, flush=True)

    def _safe_fetch(self, fetch, flat_idx: int,
                    rng: np.random.Generator):
        idx = int(flat_idx)
        for _ in range(self.max_record_retries + 1):
            if idx not in self.quarantined:
                try:
                    return fetch(idx)
                except Exception as exc:
                    # Quarantine the record whose FILE failed (it may be a
                    # randomly-drawn sibling view, not the indexed record);
                    # fall back to the index when the error names no known
                    # record. Subsequent random view draws avoid quarantined
                    # views (_draw_view), so the retry below can succeed on
                    # the same index. Packed-record errors carry the flat
                    # index directly (records.PackedDataset tags them);
                    # the file walker falls back to a path scan.
                    failed = getattr(exc, "flat_index", None)
                    if failed is None:
                        failed = self._locate_failing_record(str(exc))
                    self._quarantine(idx if failed is None else failed, exc)
                    if failed is not None and failed != idx:
                        continue  # same index, bad sibling now avoided
            idx = int(rng.integers(len(self)))
        raise RuntimeError(
            f"data: {self.max_record_retries + 1} consecutive record draws "
            f"failed or were quarantined ({len(self.quarantined)} "
            f"quarantined total under {self.root_dir!r}) — the dataset is "
            "too corrupt to keep training; see the quarantine reports "
            "above")

    def safe_pair(self, flat_idx: int, rng: np.random.Generator,
                  num_cond: int = 1) -> dict:
        """`pair` with quarantine-and-redraw instead of a fatal raise."""
        return self._safe_fetch(
            lambda i: self.pair(i, rng, num_cond=num_cond), flat_idx, rng)

    def safe_samples(self, flat_idx: int, rng: np.random.Generator,
                     num_cond: int = 1) -> List[dict]:
        """`samples` with quarantine-and-redraw; retries the WHOLE group
        from a substitute index so the instance-grouping contract (all
        records from one instance) holds even through a fault."""
        return self._safe_fetch(
            lambda i: self.samples(i, rng, num_cond=num_cond), flat_idx, rng)


class SRNDataset(FlatViewDataset):
    """All instances of a class directory (reference SceneClassDataset,
    data_loader.py:116-161), flat-indexed over (instance, view) — the
    file-walking backend (`data.backend='files'`). The packed-record
    backend (records.PackedDataset) shares every sampling/quarantine
    semantic through FlatViewDataset."""

    def __init__(self, root_dir: str, img_sidelength: int = 64,
                 max_num_instances: int = -1,
                 max_observations_per_instance: int = -1,
                 specific_observation_idcs: Optional[Sequence[int]] = None,
                 samples_per_instance: int = 1,
                 max_record_retries: int = 3):
        super().__init__(samples_per_instance=samples_per_instance,
                         max_record_retries=max_record_retries)
        self.root_dir = root_dir
        self.img_sidelength = img_sidelength
        instance_dirs = sorted(glob(os.path.join(root_dir, "*/")))
        if not instance_dirs:
            raise FileNotFoundError(f"no instances under {root_dir!r}")
        if max_num_instances != -1:
            instance_dirs = instance_dirs[:max_num_instances]

        for idx, d in enumerate(instance_dirs):
            color = _subset(glob_images(os.path.join(d, "rgb")),
                            specific_observation_idcs,
                            max_observations_per_instance)
            pose = _subset(sorted(glob(os.path.join(d, "pose", "*.txt"))),
                           specific_observation_idcs,
                           max_observations_per_instance)
            if len(color) != len(pose):
                raise ValueError(
                    f"{d}: {len(color)} images vs {len(pose)} poses")
            K, _, _, _ = parse_intrinsics(os.path.join(d, "intrinsics.txt"),
                                          trgt_sidelength=img_sidelength)
            self.instances.append(SRNInstance(
                instance_idx=idx, instance_dir=d, color_paths=color,
                pose_paths=pose, K=K, img_sidelength=img_sidelength))
        self._finalize_index()

    def _locate_failing_record(self, msg: str) -> Optional[int]:
        """Flat index of the record whose image/pose path appears in an
        error message, or None. Lets the quarantine hit the file that
        actually failed even when it was a randomly-drawn sibling of the
        indexed record. O(records) — fault-path only."""
        for obj, inst in enumerate(self.instances):
            for v, (c, p) in enumerate(zip(inst.color_paths,
                                           inst.pose_paths)):
                if c in msg or p in msg:
                    return int(self._offsets[obj]) + v
        return None
