"""Packed-record data plane: sharded record format + indexed reader.

The SRN file walker (data/srn.py) opens three files per view — fine for
one class of ShapeNet cars, hopeless at a millions-of-scenes corpus
(ROADMAP item 3): metadata walks dominate startup, random reads seek per
view, and no object store serves millions of tiny files well. This module
packs an SRN-layout tree into a few large shards once (`nvs3d pack`) and
serves training from them:

  shard-00000.nvsrec                      one record per SCENE
  ┌──────────────────────────────────────────────────────────────┐
  │ b"NVS3DRC1"          magic (8 B)                             │
  │ <II                  version, flags (8 B)                    │
  │ record 0             msgpack {name, intrinsics,              │
  │ record 1                      views: [{rgb: png-bytes,       │
  │ ...                                    pose: 16×f32-LE}]}    │
  │ footer               msgpack {instances:                     │
  │                               [[name, offset, length,        │
  │                                 num_views], ...]}            │
  │ <QQ                  footer offset, footer length (16 B)     │
  │ sha256               over bytes [0, footer end) (32 B)       │
  │ b"NVS3DEND"          end marker (8 B)                        │
  └──────────────────────────────────────────────────────────────┘

  index.json            corpus-level: ordered instance entries
                        {name, shard, offset, length, views,
                        intrinsics-text} + per-shard {file, bytes,
                        sha256} — (instance, view) → (shard, offset)
                        without touching any shard.

Design decisions:
  - RGB stays in its ORIGINAL encoded form (the source PNG/JPG bytes):
    decode + square-crop + resize remain read-time decisions, so one
    packed corpus serves every img_sidelength, and the decode chain is
    byte-for-byte the file walker's (srn.decode_rgb) — the foundation of
    the bit-identity contract between `backend='packed'` and 'files'.
  - Sharded BY SCENE: every view of an instance lives in one record, so
    the reference's same-instance pair/group sampling touches one shard
    region, and per-host sharding at shard granularity keeps instances
    whole.
  - Per-host reads: a process opens only the shards whose ordinal lands
    in its 1/process_count() slice — no host ever stats, hashes, or reads
    another host's bytes (composes with parallel/mesh.shard_batch exactly
    like the Grain path's per-host IndexSampler shards).
  - Integrity first (PR 1 quarantine semantics): every shard is re-hashed
    at open; a flipped byte or torn tail quarantines that shard's records
    BY ID (reported, skipped) and the run continues on the remaining
    shards — one bad shard costs its records, never the run. Records that
    fail decode despite a clean hash quarantine individually through the
    shared FlatViewDataset ladder, bounded by data.max_record_retries.

Fault injection: NVS3D_FI_CORRUPT_SHARD_AT / NVS3D_FI_TRUNCATE_SHARD_AT
(utils/faultinject.py) mutate the byte stream AS READ at open — the
tier-1 drills prove both quarantine lanes without touching disk.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import struct
import sys
import threading
from collections import OrderedDict
from glob import glob
from typing import Dict, List, Optional, Sequence, Tuple

import msgpack
import numpy as np

from novel_view_synthesis_3d_tpu.data.srn import (
    FlatViewDataset,
    _subset,
    decode_rgb,
    glob_images,
    load_pose,
    parse_intrinsics_text,
)
from novel_view_synthesis_3d_tpu.utils import faultinject

SHARD_MAGIC = b"NVS3DRC1"
SHARD_END = b"NVS3DEND"
SHARD_VERSION = 1
SHARD_SUFFIX = ".nvsrec"
INDEX_NAME = "index.json"
FORMAT_NAME = "nvs3d-packed"
_HEADER = struct.Struct("<II")  # version, flags
_TAIL_FIXED = struct.Struct("<QQ")  # footer offset, footer length
HEADER_LEN = len(SHARD_MAGIC) + _HEADER.size
TAIL_LEN = _TAIL_FIXED.size + 32 + len(SHARD_END)


class ShardCorrupt(RuntimeError):
    """A shard failed its open-time integrity check (bad magic, torn
    tail, sha256 mismatch, or footer/index disagreement)."""


class PackedRecordError(RuntimeError):
    """A record inside a VERIFIED shard failed to decode. Carries
    `.flat_index` so the shared quarantine ladder (FlatViewDataset.
    _safe_fetch) hits the exact record, sibling draws included."""

    def __init__(self, msg: str, flat_index: int):
        super().__init__(msg)
        self.flat_index = int(flat_index)


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------
class ShardWriter:
    """One shard file: header + scene records + footer index + hash tail.

    The sha256 covers every byte before the tail, so a reader can prove
    end-to-end integrity (including the footer it is about to trust) from
    one streaming pass."""

    def __init__(self, path: str):
        self.path = path
        self._fh = open(path + ".tmp", "wb")
        self._hash = hashlib.sha256()
        self._entries: List[list] = []
        self._write(SHARD_MAGIC + _HEADER.pack(SHARD_VERSION, 0))

    def _write(self, data: bytes) -> None:
        self._fh.write(data)
        self._hash.update(data)

    @property
    def bytes_written(self) -> int:
        return self._fh.tell()

    def add(self, name: str, payload: bytes, num_views: int) -> int:
        offset = self._fh.tell()
        self._write(payload)
        self._entries.append([name, offset, len(payload), int(num_views)])
        return offset

    def close(self) -> dict:
        """Footer + tail, fsync, atomic rename. Returns the shard's
        index.json entry (minus the file name the caller assigns)."""
        footer = msgpack.packb({"instances": self._entries},
                               use_bin_type=True)
        footer_off = self._fh.tell()
        self._write(footer)
        self._fh.write(_TAIL_FIXED.pack(footer_off, len(footer))
                       + self._hash.digest() + SHARD_END)
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._fh.close()
        os.replace(self.path + ".tmp", self.path)
        return {
            "bytes": os.path.getsize(self.path),
            "sha256": self._hash.hexdigest(),
            "num_instances": len(self._entries),
            "num_views": sum(e[3] for e in self._entries),
        }


def pack_srn(root_dir: str, out_dir: str, *, shard_mb: float = 64.0,
             max_num_instances: int = -1,
             name: Optional[str] = None,
             classes: Optional[Sequence[str]] = None,
             progress: Optional[callable] = None) -> dict:
    """Pack an SRN-layout directory into sharded records + index.json.

    Shards by scene: a shard is closed once it crosses `shard_mb` (so
    every scene's views stay together). RGB bytes are stored as found on
    disk (no re-encode — see the module docstring), poses as parsed f32,
    intrinsics as raw text. Returns the index dict that was written.

    The index gains a `meta` block — corpus identity for the mixer
    (data/corpus.py): `name` (default: the source dir's basename),
    native `resolution` (min dimension of the first image after square
    crop — what the corpus can honestly serve without upsampling),
    scene/view counts, and the `classes` vocab (default: [name]).
    `nvs3d pack --verify` cross-checks the block against the shards."""
    instance_dirs = sorted(glob(os.path.join(root_dir, "*/")))
    if not instance_dirs:
        raise FileNotFoundError(f"no instances under {root_dir!r}")
    if max_num_instances != -1:
        instance_dirs = instance_dirs[:max_num_instances]
    os.makedirs(out_dir, exist_ok=True)
    target_bytes = max(1, int(shard_mb * 1e6))

    # Resolve the corpus identity BEFORE the pack loop — the loop reuses
    # `name` for instance names, and the meta block must not inherit the
    # last instance's.
    corpus_name = name or os.path.basename(
        os.path.normpath(root_dir)) or "corpus"

    shards: List[dict] = []
    instances: List[dict] = []
    writer: Optional[ShardWriter] = None
    native_resolution: Optional[int] = None

    def close_shard():
        nonlocal writer
        meta = writer.close()
        meta = dict(file=os.path.basename(writer.path), **meta)
        shards.append(meta)
        writer = None

    for d in instance_dirs:
        name = os.path.basename(os.path.normpath(d))
        colors = glob_images(os.path.join(d, "rgb"))
        poses = sorted(glob(os.path.join(d, "pose", "*.txt")))
        if len(colors) != len(poses):
            raise ValueError(f"{d}: {len(colors)} images vs "
                             f"{len(poses)} poses")
        with open(os.path.join(d, "intrinsics.txt")) as fh:
            intrinsics = fh.read()
        views = []
        for c, p in zip(colors, poses):
            with open(c, "rb") as fh:
                rgb = fh.read()
            if native_resolution is None:
                # Native corpus resolution = the square-crop sidelength
                # of the first image (min dimension) — the largest
                # sidelength this corpus serves without upsampling.
                from PIL import Image

                with Image.open(io.BytesIO(rgb)) as im:
                    native_resolution = min(im.size)
            views.append({"rgb": rgb,
                          "pose": load_pose(p).astype("<f4").tobytes()})
        payload = msgpack.packb(
            {"name": name, "intrinsics": intrinsics, "views": views},
            use_bin_type=True)
        if writer is None:
            writer = ShardWriter(os.path.join(
                out_dir, f"shard-{len(shards):05d}{SHARD_SUFFIX}"))
        offset = writer.add(name, payload, len(views))
        instances.append({"name": name, "shard": len(shards),
                          "offset": offset, "length": len(payload),
                          "views": len(views), "intrinsics": intrinsics})
        if progress is not None:
            progress(name, len(views), len(shards))
        if writer.bytes_written >= target_bytes:
            close_shard()
    if writer is not None:
        close_shard()

    index = {
        "format": FORMAT_NAME,
        "version": SHARD_VERSION,
        "source": os.path.abspath(root_dir),
        "num_instances": len(instances),
        "num_views": sum(e["views"] for e in instances),
        "meta": {
            "name": corpus_name,
            "resolution": native_resolution,
            "num_scenes": len(instances),
            "num_views": sum(e["views"] for e in instances),
            "classes": (list(classes) if classes else [corpus_name]),
        },
        "shards": shards,
        "instances": instances,
    }
    tmp = os.path.join(out_dir, INDEX_NAME + ".tmp")
    with open(tmp, "w") as fh:
        json.dump(index, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, os.path.join(out_dir, INDEX_NAME))
    return index


# ---------------------------------------------------------------------------
# Shard open + verification
# ---------------------------------------------------------------------------
def read_shard_footer(path: str, ordinal: int = 0, *,
                      fault_injection: bool = False) -> dict:
    """Open + VERIFY one shard (magic, end marker, sha256 re-hash over
    header+records+footer) and return its footer dict. Raises
    ShardCorrupt on any integrity failure — a torn tail (interrupted
    write) and a flipped byte are both caught here, before any record
    bytes are trusted.

    The whole shard is read once for the hash (transient — the bytes are
    dropped on return; record access later seeks the file directly).
    `fault_injection=True` lets the NVS3D_FI_*_SHARD_AT env points mutate
    the stream as read (reader path only; `nvs3d pack --verify` sees the
    real bytes)."""
    with open(path, "rb") as fh:
        data = fh.read()
    if fault_injection:
        data = faultinject.maybe_corrupt_shard_bytes(ordinal, data)
    if len(data) < HEADER_LEN + TAIL_LEN:
        raise ShardCorrupt(f"{path}: truncated ({len(data)} bytes — "
                           "shorter than header + tail)")
    if data[:len(SHARD_MAGIC)] != SHARD_MAGIC:
        raise ShardCorrupt(f"{path}: bad magic (not a packed shard)")
    version, _ = _HEADER.unpack(
        data[len(SHARD_MAGIC):HEADER_LEN])
    if version != SHARD_VERSION:
        raise ShardCorrupt(f"{path}: shard version {version} != "
                           f"{SHARD_VERSION}")
    tail = data[-TAIL_LEN:]
    if tail[-len(SHARD_END):] != SHARD_END:
        raise ShardCorrupt(f"{path}: torn tail (end marker missing — "
                           "interrupted write?)")
    footer_off, footer_len = _TAIL_FIXED.unpack(tail[:_TAIL_FIXED.size])
    digest = tail[_TAIL_FIXED.size:_TAIL_FIXED.size + 32]
    body = data[:-TAIL_LEN]
    if footer_off + footer_len != len(body):
        raise ShardCorrupt(f"{path}: footer bounds ({footer_off}+"
                           f"{footer_len}) disagree with file size")
    if hashlib.sha256(body).digest() != digest:
        raise ShardCorrupt(f"{path}: sha256 mismatch — flipped byte or "
                           "partial write")
    try:
        footer = msgpack.unpackb(body[footer_off:footer_off + footer_len],
                                 raw=False)
    except Exception as exc:
        raise ShardCorrupt(f"{path}: footer undecodable: {exc}") from exc
    if not isinstance(footer, dict) or "instances" not in footer:
        raise ShardCorrupt(f"{path}: footer missing instance table")
    return footer


def verify_packed(root_dir: str, *, decode: str = "first") -> List[str]:
    """Integrity sweep over a packed corpus (`nvs3d pack --verify`).

    Per shard: re-hash + footer check (read_shard_footer), then
    cross-check every index.json entry against the footer, unpack every
    record, and — decode='first' (default) — PNG-decode one view per
    record and parse its pose as a torn-content tripwire ('all' decodes
    every view; 'none' skips decode). Returns a list of problem strings
    (empty = corpus verified)."""
    problems: List[str] = []
    index_path = os.path.join(root_dir, INDEX_NAME)
    try:
        with open(index_path) as fh:
            index = json.load(fh)
    except (OSError, json.JSONDecodeError) as exc:
        return [f"{index_path}: unreadable index ({exc})"]
    if index.get("format") != FORMAT_NAME:
        return [f"{index_path}: format {index.get('format')!r} != "
                f"{FORMAT_NAME!r}"]
    # Corpus metadata cross-check (the mixer trusts this block for its
    # resolution-mismatch refusal — a stale block must fail verify).
    meta = index.get("meta")
    if meta is not None:
        n_inst = len(index.get("instances", []))
        n_views = sum(int(e["views"]) for e in index.get("instances", []))
        if int(meta.get("num_scenes", -1)) != n_inst:
            problems.append(
                f"{index_path}: meta.num_scenes={meta.get('num_scenes')} "
                f"disagrees with the {n_inst} indexed instances")
        if int(meta.get("num_views", -1)) != n_views:
            problems.append(
                f"{index_path}: meta.num_views={meta.get('num_views')} "
                f"disagrees with the {n_views} indexed views")
        if not meta.get("name"):
            problems.append(f"{index_path}: meta.name is empty")
        if not meta.get("classes"):
            problems.append(f"{index_path}: meta.classes vocab is empty")
    first_decode_res: Optional[int] = None
    by_shard: Dict[int, List[dict]] = {}
    for e in index.get("instances", []):
        by_shard.setdefault(int(e["shard"]), []).append(e)
    for ordinal, smeta in enumerate(index.get("shards", [])):
        path = os.path.join(root_dir, smeta["file"])
        try:
            footer = read_shard_footer(path, ordinal)
        except (ShardCorrupt, OSError) as exc:
            problems.append(str(exc))
            continue
        if smeta.get("sha256"):
            with open(path, "rb") as fh:
                body = fh.read()[:-TAIL_LEN]
            if hashlib.sha256(body).hexdigest() != smeta["sha256"]:
                problems.append(f"{path}: sha256 differs from index.json")
        footer_map = {e[0]: tuple(e[1:]) for e in footer["instances"]}
        for entry in by_shard.get(ordinal, []):
            got = footer_map.get(entry["name"])
            want = (entry["offset"], entry["length"], entry["views"])
            if got != want:
                problems.append(
                    f"{path}: index entry {entry['name']!r} {want} "
                    f"disagrees with shard footer {got}")
                continue
            try:
                with open(path, "rb") as fh:
                    fh.seek(entry["offset"])
                    rec = msgpack.unpackb(fh.read(entry["length"]),
                                          raw=False)
                if rec["name"] != entry["name"]:
                    raise ValueError(
                        f"record name {rec['name']!r} != index entry")
                if len(rec["views"]) != entry["views"]:
                    raise ValueError(
                        f"{len(rec['views'])} views != "
                        f"{entry['views']} in index")
                to_decode = (range(len(rec["views"]))
                             if decode == "all"
                             else ([0] if decode == "first" else []))
                for v in to_decode:
                    view = rec["views"][v]
                    img = decode_rgb(io.BytesIO(view["rgb"]))
                    if first_decode_res is None:
                        first_decode_res = int(min(img.shape[:2]))
                    pose = np.frombuffer(view["pose"], dtype="<f4")
                    if pose.shape != (16,):
                        raise ValueError(
                            f"view {v}: pose has {pose.size} floats")
            except Exception as exc:
                problems.append(
                    f"{path}: record {entry['name']!r}: "
                    f"{type(exc).__name__}: {exc}")
    if (meta is not None and meta.get("resolution")
            and first_decode_res is not None
            and int(meta["resolution"]) != first_decode_res):
        problems.append(
            f"{index_path}: meta.resolution={meta['resolution']} but the "
            f"first decoded view is {first_decode_res}px — the mixer's "
            "resolution guard would trust a lie; re-pack")
    return problems


# ---------------------------------------------------------------------------
# Reader
# ---------------------------------------------------------------------------
class PackedInstance:
    """One scene of a packed corpus — the read-side twin of SRNInstance.
    Decoding is delegated to the owning dataset (shard seeks + scene
    cache); only metadata lives here."""

    __slots__ = ("_ds", "instance_idx", "instance_dir", "K",
                 "img_sidelength", "view_ids")

    def __init__(self, ds: "PackedDataset", instance_idx: int, name: str,
                 K: np.ndarray, img_sidelength: int,
                 view_ids: Sequence[int]):
        self._ds = ds
        self.instance_idx = instance_idx
        self.instance_dir = name  # quarantine reports use the basename
        self.K = K
        self.img_sidelength = img_sidelength
        self.view_ids = list(view_ids)

    def __len__(self) -> int:
        return len(self.view_ids)

    def view(self, idx: int) -> Tuple[np.ndarray, np.ndarray]:
        """(image HWC [-1,1], pose 4×4) for one observation."""
        return self._ds._decode_view(self.instance_idx, idx)


class PackedDataset(FlatViewDataset):
    """Indexed reader over a packed corpus (`nvs3d pack` output) with
    per-host sharding at shard granularity.

    Drop-in for SRNDataset (same flat indexing, pair/samples semantics,
    safe_* quarantine ladder — all shared via FlatViewDataset), but:
      - opens ONLY the shards whose ordinal % shard_count == shard_index
        (each host reads its 1/process_count() slice);
      - RE-HASHES every opened shard: a corrupt or torn shard quarantines
        its records by id at open (loud report, run continues on the
        remaining shards) instead of surfacing as garbage batches later;
      - serves record bytes by (shard, offset) seek with a small LRU of
        unpacked scenes (instance-grouped sampling touches one scene
        repeatedly) — no per-view file opens, no metadata walk.
    """

    def __init__(self, root_dir: str, img_sidelength: int = 64,
                 max_num_instances: int = -1,
                 max_observations_per_instance: int = -1,
                 specific_observation_idcs: Optional[Sequence[int]] = None,
                 samples_per_instance: int = 1,
                 max_record_retries: int = 3,
                 shard_index: int = 0, shard_count: int = 1,
                 scene_cache: int = 64):
        super().__init__(samples_per_instance=samples_per_instance,
                         max_record_retries=max_record_retries)
        self.root_dir = root_dir
        self.img_sidelength = img_sidelength
        index_path = os.path.join(root_dir, INDEX_NAME)
        if not os.path.exists(index_path):
            raise FileNotFoundError(
                f"no {INDEX_NAME} under {root_dir!r} — not a packed "
                "corpus; create one with `nvs3d pack <srn_dir> --out "
                f"{root_dir}` or set data.backend='files'")
        with open(index_path) as fh:
            index = json.load(fh)
        if index.get("format") != FORMAT_NAME:
            raise ValueError(
                f"{index_path}: format {index.get('format')!r} != "
                f"{FORMAT_NAME!r}")
        if not 0 <= shard_index < max(1, shard_count):
            raise ValueError(
                f"shard_index {shard_index} outside [0, {shard_count})")

        entries = list(index["instances"])
        if max_num_instances != -1:
            # Global-order subset FIRST (same records on every host),
            # then the per-host shard slice below.
            entries = entries[:max_num_instances]
        if shard_count > 1:
            entries = [e for e in entries
                       if int(e["shard"]) % shard_count == shard_index]
            if not entries:
                raise ValueError(
                    f"host slice {shard_index}/{shard_count} of "
                    f"{root_dir!r} holds no shards "
                    f"({len(index['shards'])} total) — repack with a "
                    "smaller --shard-mb so every host gets at least one")

        self._entries = entries
        self._shard_paths: Dict[int, str] = {
            int(e["shard"]): os.path.join(root_dir,
                                          index["shards"][int(e["shard"])]
                                          ["file"])
            for e in entries}
        self._shard_locks: Dict[int, threading.Lock] = {
            s: threading.Lock() for s in self._shard_paths}
        self._cache: "OrderedDict[int, dict]" = OrderedDict()
        self._cache_lock = threading.Lock()
        self._scene_cache = max(1, scene_cache)

        # Open + verify this host's shard slice. A failing shard
        # quarantines its records by id; the survivors keep training.
        by_shard: Dict[int, List[dict]] = {}
        for e in entries:
            by_shard.setdefault(int(e["shard"]), []).append(e)
        bad_shards: Dict[int, str] = {}
        for ordinal in sorted(self._shard_paths):
            try:
                footer = read_shard_footer(self._shard_paths[ordinal],
                                           ordinal, fault_injection=True)
            except (ShardCorrupt, OSError) as exc:
                bad_shards[ordinal] = f"{type(exc).__name__}: {exc}"
                continue
            footer_map = {e[0]: tuple(e[1:])
                          for e in footer["instances"]}
            for e in by_shard.get(ordinal, ()):
                if footer_map.get(e["name"]) != (e["offset"], e["length"],
                                                 e["views"]):
                    bad_shards[ordinal] = (
                        "footer disagrees with index.json (stale or "
                        "swapped shard file)")
                    break

        for idx, e in enumerate(entries):
            selected = _subset(list(range(int(e["views"]))),
                               specific_observation_idcs,
                               max_observations_per_instance)
            K, _, _, _ = parse_intrinsics_text(
                e["intrinsics"], trgt_sidelength=img_sidelength)
            self.instances.append(PackedInstance(
                self, idx, e["name"], K, img_sidelength, selected))
        self._finalize_index()

        self.shards_open = len(self._shard_paths) - len(bad_shards)
        self.shards_quarantined = len(bad_shards)
        for ordinal, reason in sorted(bad_shards.items()):
            names = [e["name"] for e in entries
                     if int(e["shard"]) == ordinal]
            ids: List[int] = []
            for obj, e in enumerate(entries):
                if int(e["shard"]) == ordinal:
                    ids.extend(range(int(self._offsets[obj]),
                                     int(self._offsets[obj + 1])))
            self.quarantined.update(ids)
            report = {
                "shard": os.path.basename(self._shard_paths[ordinal]),
                "records": len(ids),
                "instances": names,
                "error": reason,
            }
            self.fault_reports.append(report)
            print(f"warning: data fault: shard "
                  f"{report['shard']} quarantined at open "
                  f"({len(ids)} records across {len(names)} instances): "
                  f"{reason}", file=sys.stderr, flush=True)
        if len(self) > 0 and len(self.quarantined) >= len(self):
            raise RuntimeError(
                f"packed corpus {root_dir!r}: every local shard failed "
                "verification — nothing left to train on; re-pack or "
                "restore the shards (see the quarantine reports above)")
        self._publish_gauges()

    def _publish_gauges(self) -> None:
        """Data-plane health on the shared obs registry: shard + record
        quarantine state next to the trainer's step gauges."""
        try:
            from novel_view_synthesis_3d_tpu import obs

            reg = obs.get_registry()
            reg.gauge("nvs3d_data_shards_open",
                      "packed shards this process serves from").set(
                          self.shards_open)
            reg.gauge("nvs3d_data_shards_quarantined",
                      "packed shards quarantined at open "
                      "(hash/tail failure)").set(self.shards_quarantined)
            reg.gauge("nvs3d_data_records_quarantined",
                      "records quarantined by the data fault ladder").set(
                          len(self.quarantined))
        except Exception:
            pass  # telemetry must never fail the data path

    # -- record access --------------------------------------------------
    def _scene(self, obj: int) -> dict:
        """Unpacked scene record for instance `obj` (LRU-cached; the seek
        + read is serialized per shard, the msgpack decode is not)."""
        with self._cache_lock:
            rec = self._cache.get(obj)
            if rec is not None:
                self._cache.move_to_end(obj)
                return rec
        e = self._entries[obj]
        ordinal = int(e["shard"])
        with self._shard_locks[ordinal]:
            with open(self._shard_paths[ordinal], "rb") as fh:
                fh.seek(int(e["offset"]))
                payload = fh.read(int(e["length"]))
        rec = msgpack.unpackb(payload, raw=False)
        if (rec.get("name") != e["name"]
                or len(rec.get("views", ())) != int(e["views"])):
            raise ValueError(
                f"record at {self._shard_paths[ordinal]}:{e['offset']} "
                "does not match its index entry (corrupt offset?)")
        with self._cache_lock:
            self._cache[obj] = rec
            while len(self._cache) > self._scene_cache:
                self._cache.popitem(last=False)
        return rec

    def _decode_view(self, obj: int, idx: int
                     ) -> Tuple[np.ndarray, np.ndarray]:
        inst = self.instances[obj]
        try:
            rec = self._scene(obj)
            view = rec["views"][inst.view_ids[idx]]
            rgb = decode_rgb(io.BytesIO(view["rgb"]), self.img_sidelength)
            pose = np.frombuffer(view["pose"],
                                 dtype="<f4").reshape(4, 4).astype(
                                     np.float32)
        except Exception as exc:
            flat = int(self._offsets[obj]) + int(idx)
            raise PackedRecordError(
                f"packed record {inst.instance_dir!r} view {idx} "
                f"(flat {flat}): {type(exc).__name__}: {exc}",
                flat_index=flat) from exc
        return rgb, pose

    def _quarantine(self, flat_idx: int, exc: Exception) -> None:
        super()._quarantine(flat_idx, exc)
        self._publish_gauges()


def make_packed_dataset(cfg, *, shard_index: int = 0,
                        shard_count: int = 1) -> PackedDataset:
    """PackedDataset from a DataConfig (`data.backend='packed'`:
    data.root_dir IS the packed corpus directory)."""
    return PackedDataset(
        root_dir=cfg.root_dir,
        img_sidelength=cfg.img_sidelength,
        max_num_instances=cfg.max_num_instances,
        max_observations_per_instance=cfg.max_observations_per_instance,
        specific_observation_idcs=cfg.specific_observation_idcs,
        samples_per_instance=cfg.samples_per_instance,
        max_record_retries=cfg.max_record_retries,
        shard_index=shard_index,
        shard_count=shard_count,
    )
