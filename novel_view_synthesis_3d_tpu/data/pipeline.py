"""Input pipeline: Grain multiprocess loading with per-host sharding.

Replaces the reference's torch `DataLoader` (train.py:108-113) — the one
native-code subsystem of the reference's data path (SURVEY.md §2.4) — with
Grain worker processes (C++-backed shared-memory queues) + deterministic
per-host sharding, and a dependency-free in-process iterator as fallback.

Design:
  - the data source indexes (instance, view) pairs; the conditioning view is
    the indexed record, the target view is drawn by Grain's per-record RNG
    (deterministic in (seed, epoch, index) — reproducible across restarts,
    unlike the reference's np.random in worker processes);
  - records are CLEAN image pairs; forward noising runs on device in the
    train step (SURVEY.md §7 ledger);
  - sharding: each process reads only its 1/jax.process_count() slice —
    the per-host Grain shards that feed
    `jax.make_array_from_process_local_data` (parallel/mesh.shard_batch).
"""

from __future__ import annotations

from collections import deque
from typing import Iterator, Optional

import numpy as np

from novel_view_synthesis_3d_tpu.config import DataConfig
from novel_view_synthesis_3d_tpu.data.srn import SRNDataset


def make_dataset(cfg: DataConfig, *, shard_index: int = 0,
                 shard_count: int = 1):
    """Dataset for a DataConfig, dispatching on `data.backend`.

    'files' (default): the SRN file walker. 'packed': the sharded-record
    reader (data/records.py; data.root_dir is the packed corpus dir), in
    which case `shard_index`/`shard_count` select this host's
    shard-granular slice — the files backend ignores them (its per-host
    sharding happens at the index-sampler level instead)."""
    if getattr(cfg, "backend", "files") == "packed":
        from novel_view_synthesis_3d_tpu.data.records import (
            make_packed_dataset)

        return make_packed_dataset(cfg, shard_index=shard_index,
                                   shard_count=shard_count)
    return SRNDataset(
        root_dir=cfg.root_dir,
        img_sidelength=cfg.img_sidelength,
        max_num_instances=cfg.max_num_instances,
        max_observations_per_instance=cfg.max_observations_per_instance,
        specific_observation_idcs=cfg.specific_observation_idcs,
        samples_per_instance=cfg.samples_per_instance,
        max_record_retries=cfg.max_record_retries,
    )


# ---------------------------------------------------------------------------
# Grain pipeline (multiprocess, deterministic, per-host sharded)
# ---------------------------------------------------------------------------
class _PairSource:
    """grain RandomAccessDataSource over flat (instance, view) indices."""

    def __init__(self, dataset: SRNDataset):
        self._ds = dataset

    def __len__(self) -> int:
        return len(self._ds)

    def __getitem__(self, idx: int) -> int:
        # Defer ALL IO to the random-map transform (which owns the rng that
        # picks the target view); the source just passes the index through.
        return int(idx)


def make_grain_loader(dataset: SRNDataset, batch_size: int,
                      *, seed: int = 0, num_workers: int = 8,
                      num_epochs: Optional[int] = None,
                      shard_index: Optional[int] = None,
                      shard_count: Optional[int] = None,
                      drop_remainder: bool = True,
                      num_cond: int = 1):
    """Grain DataLoader yielding batched numpy dicts (per-host shard).

    With dataset.samples_per_instance > 1 the reference's instance-grouped
    batching (data_loader.py:183-195) applies: each sampled index yields
    that many records of ONE instance (SRNDataset.samples — the indexed
    observation first), stacked on a leading group axis inside the worker;
    the batch of batch_size/spi groups is then flattened back so groups
    occupy consecutive batch slots, exactly like iter_batches' grouped
    path. batch_size still counts MODEL samples.
    """
    import grain.python as pygrain
    import jax

    spi = getattr(dataset, "samples_per_instance", 1)
    if batch_size % spi != 0:
        raise ValueError(
            f"batch_size {batch_size} not divisible by "
            f"samples_per_instance {spi}")

    shard_index = jax.process_index() if shard_index is None else shard_index
    shard_count = jax.process_count() if shard_count is None else shard_count

    ds_ref = dataset

    # Fault wrapper: corrupt records are quarantined-and-redrawn inside
    # the worker (SRNDataset.safe_*) instead of killing the worker pool.
    # Duck-typed so non-SRN datasets without safe_* still work.
    fetch_pair = getattr(ds_ref, "safe_pair", ds_ref.pair)
    fetch_samples = getattr(ds_ref, "safe_samples", None) or ds_ref.samples

    class PairTransform(pygrain.RandomMapTransform):
        def random_map(self, idx, rng: np.random.Generator):
            return fetch_pair(int(idx), rng, num_cond=num_cond)

    class GroupTransform(pygrain.RandomMapTransform):
        def random_map(self, idx, rng: np.random.Generator):
            records = fetch_samples(int(idx), rng, num_cond=num_cond)
            return {k: np.stack([r[k] for r in records])
                    for k in records[0]}

    class FlattenGroups(pygrain.MapTransform):
        def map(self, batch: dict) -> dict:
            # (draws, spi, ...) -> (draws*spi, ...): groups stay
            # consecutive in the flattened batch.
            return {k: v.reshape((-1,) + v.shape[2:])
                    for k, v in batch.items()}

    operations = [
        PairTransform() if spi == 1 else GroupTransform(),
        pygrain.Batch(batch_size=batch_size // spi,
                      drop_remainder=drop_remainder),
    ]
    if spi > 1:
        operations.append(FlattenGroups())

    sampler = pygrain.IndexSampler(
        num_records=len(dataset),
        shard_options=pygrain.ShardOptions(
            shard_index=shard_index, shard_count=shard_count,
            drop_remainder=True),
        shuffle=True,
        num_epochs=num_epochs,
        seed=seed,
    )
    return pygrain.DataLoader(
        data_source=_PairSource(dataset),
        sampler=sampler,
        operations=operations,
        worker_count=num_workers,
    )


# ---------------------------------------------------------------------------
# In-process fallback iterator (tests, debugging, tiny datasets)
# ---------------------------------------------------------------------------
def iter_batches(dataset: SRNDataset, batch_size: int, *, seed: int = 0,
                 shard_index: int = 0, shard_count: int = 1,
                 num_cond: int = 1) -> Iterator[dict]:
    """Infinite shuffled batch iterator without worker processes.

    With dataset.samples_per_instance > 1 each index draw contributes that
    many consecutive batch slots from ONE instance (reference
    data_loader.py:183-195 semantics, where the torch collate flattens the
    per-item observation list); batch_size still counts MODEL samples, so
    it must be a multiple of samples_per_instance.
    """
    rng = np.random.default_rng(seed + shard_index)
    spi = getattr(dataset, "samples_per_instance", 1)
    if batch_size % spi != 0:
        raise ValueError(
            f"batch_size {batch_size} not divisible by "
            f"samples_per_instance {spi}")
    draws = batch_size // spi
    n = len(dataset)
    local = np.arange(shard_index, n, shard_count)
    if len(local) < draws:
        # Drop-last semantics (matching the Grain path and the reference's
        # DataLoader(drop_last=True)) would yield ZERO batches here; without
        # this check the while-True below would spin forever producing
        # nothing — a silent 100%-CPU hang instead of an error.
        raise ValueError(
            f"dataset shard has {len(local)} records but the batch needs "
            f"{draws} index draws — with drop-last batching no batch can "
            "ever be formed; lower train.batch_size or provide more data")
    # Fault wrapper (duck-typed: any dataset exposing .pair() works here;
    # SRNDataset's safe_* variants add quarantine-and-redraw on top).
    fetch_pair = getattr(dataset, "safe_pair", dataset.pair)
    fetch_samples = (getattr(dataset, "safe_samples", None)
                     or getattr(dataset, "samples", None))
    while True:
        order = rng.permutation(local)
        for start in range(0, len(order) - draws + 1, draws):
            if spi == 1:
                records = [fetch_pair(int(i), rng, num_cond=num_cond)
                           for i in order[start:start + draws]]
            else:
                records = [r for i in order[start:start + draws]
                           for r in fetch_samples(int(i), rng,
                                                  num_cond=num_cond)]
            yield {k: np.stack([r[k] for r in records]) for k in records[0]}


def cycle(loader) -> Iterator[dict]:
    """Loop a (possibly finite) loader forever (reference train.py:18-21)."""
    while True:
        count = 0
        for item in loader:
            count += 1
            yield item
        if count == 0:
            raise RuntimeError("empty data loader")


# ---------------------------------------------------------------------------
# Compute-overlapped loader for the packed backend (data.backend='packed')
# ---------------------------------------------------------------------------
class PipelinedLoader:
    """Bounded decode/augment worker pool over a FlatViewDataset, yielding
    batches in deterministic order while host decode overlaps device
    compute (MinatoLoader's observation, PAPERS.md: accelerators idle on
    eager, file-granular preprocessing — so decode must be off the step
    loop's critical path).

    Split made possible by FlatViewDataset's plan/assemble halves:

      coordinator (caller's thread): draws batch PLANS with the single
        sequential rng — exactly the draw order of `iter_batches`, so the
        clean-path stream is BIT-IDENTICAL to the in-process iterator for
        the same (seed, epoch, index), k>1 and instance-grouped sampling
        included;
      worker pool: decodes each draw's views (PNG decode + resize — the
        actual CPU cost) concurrently, up to `depth` batches ahead;
      __next__: pops the oldest batch, tops the pipeline back up BEFORE
        blocking on its futures, and stacks records in plan order.

    Composes with the trainer's _DevicePrefetcher: this pool hides decode
    latency, the prefetcher hides the host→device upload — together the
    armed `data_fetch` phase degenerates to a queue pop (the acceptance
    target: data_fetch p99 ≈ 0 relative to train_step).

    Fault semantics (PR 1 ladder, one deviation): a draw whose decode
    fails is quarantined BY ID exactly as in the sync path, but its
    substitute is drawn from a dedicated redraw rng — the main rng's
    stream must not depend on decode timing. Clean runs are bit-identical;
    faulty runs quarantine the same records but may substitute different
    ones. Substitution is bounded by dataset.max_record_retries, then
    raises (too-corrupt-to-train), and whole-group retry keeps the
    instance-grouping contract for samples_per_instance > 1.
    """

    def __init__(self, dataset, batch_size: int, *, seed: int = 0,
                 shard_index: int = 0, num_cond: int = 1,
                 workers: int = 4, depth: int = 2,
                 skip_batches: int = 0):
        from concurrent.futures import ThreadPoolExecutor

        spi = getattr(dataset, "samples_per_instance", 1)
        if batch_size % spi != 0:
            raise ValueError(
                f"batch_size {batch_size} not divisible by "
                f"samples_per_instance {spi}")
        self._ds = dataset
        self._spi = spi
        self._num_cond = num_cond
        self._draws = batch_size // spi
        self._rng = np.random.default_rng(seed + shard_index)
        # Fault-substitute stream, decoupled from the main rng (see class
        # docstring). SeedSequence keeps it deterministic per (seed, host).
        self._redraw_rng = np.random.default_rng(
            np.random.SeedSequence([seed + shard_index, 0x5EED]))
        self._live = dataset.live_indices()
        if len(self._live) < self._draws:
            raise ValueError(
                f"dataset shard has {len(self._live)} live records but the "
                f"batch needs {self._draws} index draws — with drop-last "
                "batching no batch can ever be formed; lower "
                "train.batch_size or provide more data")
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, workers),
            thread_name_prefix="nvs3d-decode")
        self._depth = max(1, depth)
        self._pending: deque = deque()
        self._plans = self._plan_stream()
        self._init_gauges()
        # Mid-run resume fast-forward (train/ladder.py): replay the first
        # `skip_batches` batches' PLANNING — the rng draws, not the
        # decodes — so the first batch actually yielded is bit-identical
        # to batch skip_batches of an uninterrupted run. Must happen
        # BEFORE priming, which consumes plans.
        for _ in range(max(0, skip_batches)):
            for i in next(self._plans):
                self._plan_draw_safe(i)
        # Prime the pipeline: decode starts NOW, so by the time the
        # consumer (trainer init, then the device prefetcher) wants the
        # first batch it is already in flight or done.
        while len(self._pending) < self._depth:
            self._submit_next()

    # -- telemetry ------------------------------------------------------
    def _init_gauges(self) -> None:
        try:
            from novel_view_synthesis_3d_tpu import obs

            reg = obs.get_registry()
            self._c_batches = reg.counter(
                "nvs3d_data_batches_total",
                "batches assembled by the pipelined loader")
            self._c_decode_errors = reg.counter(
                "nvs3d_data_decode_errors_total",
                "record decodes that failed and were quarantined")
            self._g_ready = reg.gauge(
                "nvs3d_data_ready_batches",
                "pipelined batches fully decoded and waiting")
        except Exception:  # telemetry must never fail the data path
            self._c_batches = self._c_decode_errors = self._g_ready = None

    # -- planning (sequential, rng-owning) ------------------------------
    def _plan_stream(self):
        """Infinite per-epoch permutation stream — iter_batches' loop
        structure verbatim (drop-last within each epoch)."""
        while True:
            order = self._rng.permutation(self._live)
            for start in range(0, len(order) - self._draws + 1,
                               self._draws):
                yield [int(i) for i in order[start:start + self._draws]]

    def _plan_draw(self, flat_idx: int, rng) -> list:
        """Plans for one index draw: [pair plan] or the spi-group plans."""
        if self._spi == 1:
            return [self._ds._plan_pair(flat_idx, rng,
                                        num_cond=self._num_cond)]
        return self._ds._plan_samples(flat_idx, rng,
                                      num_cond=self._num_cond)

    def _plan_draw_safe(self, flat_idx: int) -> list:
        """Main-rng plan with redraw-rng substitution on plan-time faults
        (quarantined index, injected record fault)."""
        if flat_idx not in self._ds.quarantined:
            try:
                return self._plan_draw(flat_idx, self._rng)
            except Exception as exc:
                self._ds._quarantine(
                    getattr(exc, "flat_index", flat_idx), exc)
        return self._substitute_plan()[1]

    def _substitute_plan(self) -> tuple:
        """(substitute_flat_idx, plans) from the redraw rng, bounded."""
        for _ in range(self._ds.max_record_retries + 1):
            j = int(self._redraw_rng.integers(len(self._ds)))
            if j in self._ds.quarantined:
                continue
            try:
                return j, self._plan_draw(j, self._redraw_rng)
            except Exception as exc:
                self._ds._quarantine(getattr(exc, "flat_index", j), exc)
        raise RuntimeError(
            f"data: {self._ds.max_record_retries + 1} consecutive "
            f"substitute draws failed or were quarantined "
            f"({len(self._ds.quarantined)} quarantined total under "
            f"{self._ds.root_dir!r}) — the dataset is too corrupt to "
            "keep training; see the quarantine reports above")

    # -- decode (worker pool) -------------------------------------------
    def _decode_draw(self, plans: list) -> list:
        return [self._ds._assemble_pair(p) for p in plans]

    def _submit_next(self) -> None:
        idxs = next(self._plans)
        specs = []
        for i in idxs:
            plans = self._plan_draw_safe(i)
            specs.append((i, self._pool.submit(self._decode_draw, plans)))
        self._pending.append(specs)

    def _substitute_decoded(self, flat_idx: int, exc: Exception) -> list:
        """A draw's decode failed mid-pipeline: quarantine the exact
        failing record, then plan+decode a substitute draw inline
        (bounded; whole group replaced so instance grouping holds)."""
        self._ds._quarantine(getattr(exc, "flat_index", flat_idx), exc)
        if self._c_decode_errors is not None:
            self._c_decode_errors.inc()
        last: Exception = exc
        for _ in range(self._ds.max_record_retries + 1):
            sub_idx, plans = self._substitute_plan()
            try:
                return self._decode_draw(plans)
            except Exception as exc2:
                self._ds._quarantine(
                    getattr(exc2, "flat_index", sub_idx), exc2)
                last = exc2
        raise RuntimeError(
            f"data: substitute decodes kept failing "
            f"({len(self._ds.quarantined)} quarantined total under "
            f"{self._ds.root_dir!r}) — the dataset is too corrupt to "
            f"keep training; last error: {last}")

    # -- iteration ------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self) -> dict:
        specs = self._pending.popleft()
        # Top up BEFORE blocking: the pool keeps `depth` batches decoding
        # while the caller waits on (usually-done) futures.
        self._submit_next()
        records = []
        for flat_idx, fut in specs:
            try:
                records.extend(fut.result())
            except Exception as exc:
                records.extend(self._substitute_decoded(flat_idx, exc))
        if self._c_batches is not None:
            self._c_batches.inc()
            self._g_ready.set(sum(
                1 for s in self._pending if all(f.done() for _, f in s)))
        return {k: np.stack([r[k] for r in records]) for k in records[0]}

    def stop(self) -> None:
        """Shut the worker pool down (idempotent). The loader is dead
        afterwards — only call when the run is over."""
        self._pool.shutdown(wait=False, cancel_futures=True)


def make_packed_loader(dataset, batch_size: int, *, seed: int = 0,
                       shard_index: int = 0, num_cond: int = 1,
                       workers: int = 4, depth: int = 2,
                       skip_batches: int = 0) -> PipelinedLoader:
    """Compute-overlapped loader for `data.backend='packed'`.

    `shard_index` here only decorrelates the per-host rng (seed +
    shard_index) — the per-host DATA slice already happened at
    PackedDataset construction (shard-granular). `workers`/`depth` come
    from data.num_workers / data.prefetch; workers is clamped to >= 1
    (a num_workers=0 debug config still needs one decode thread)."""
    return PipelinedLoader(dataset, batch_size, seed=seed,
                           shard_index=shard_index, num_cond=num_cond,
                           workers=workers, depth=depth,
                           skip_batches=skip_batches)
