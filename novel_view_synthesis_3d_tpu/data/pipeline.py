"""Input pipeline: Grain multiprocess loading with per-host sharding.

Replaces the reference's torch `DataLoader` (train.py:108-113) — the one
native-code subsystem of the reference's data path (SURVEY.md §2.4) — with
Grain worker processes (C++-backed shared-memory queues) + deterministic
per-host sharding, and a dependency-free in-process iterator as fallback.

Design:
  - the data source indexes (instance, view) pairs; the conditioning view is
    the indexed record, the target view is drawn by Grain's per-record RNG
    (deterministic in (seed, epoch, index) — reproducible across restarts,
    unlike the reference's np.random in worker processes);
  - records are CLEAN image pairs; forward noising runs on device in the
    train step (SURVEY.md §7 ledger);
  - sharding: each process reads only its 1/jax.process_count() slice —
    the per-host Grain shards that feed
    `jax.make_array_from_process_local_data` (parallel/mesh.shard_batch).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from novel_view_synthesis_3d_tpu.config import DataConfig
from novel_view_synthesis_3d_tpu.data.srn import SRNDataset


def make_dataset(cfg: DataConfig) -> SRNDataset:
    return SRNDataset(
        root_dir=cfg.root_dir,
        img_sidelength=cfg.img_sidelength,
        max_num_instances=cfg.max_num_instances,
        max_observations_per_instance=cfg.max_observations_per_instance,
        specific_observation_idcs=cfg.specific_observation_idcs,
        samples_per_instance=cfg.samples_per_instance,
        max_record_retries=cfg.max_record_retries,
    )


# ---------------------------------------------------------------------------
# Grain pipeline (multiprocess, deterministic, per-host sharded)
# ---------------------------------------------------------------------------
class _PairSource:
    """grain RandomAccessDataSource over flat (instance, view) indices."""

    def __init__(self, dataset: SRNDataset):
        self._ds = dataset

    def __len__(self) -> int:
        return len(self._ds)

    def __getitem__(self, idx: int) -> int:
        # Defer ALL IO to the random-map transform (which owns the rng that
        # picks the target view); the source just passes the index through.
        return int(idx)


def make_grain_loader(dataset: SRNDataset, batch_size: int,
                      *, seed: int = 0, num_workers: int = 8,
                      num_epochs: Optional[int] = None,
                      shard_index: Optional[int] = None,
                      shard_count: Optional[int] = None,
                      drop_remainder: bool = True,
                      num_cond: int = 1):
    """Grain DataLoader yielding batched numpy dicts (per-host shard).

    With dataset.samples_per_instance > 1 the reference's instance-grouped
    batching (data_loader.py:183-195) applies: each sampled index yields
    that many records of ONE instance (SRNDataset.samples — the indexed
    observation first), stacked on a leading group axis inside the worker;
    the batch of batch_size/spi groups is then flattened back so groups
    occupy consecutive batch slots, exactly like iter_batches' grouped
    path. batch_size still counts MODEL samples.
    """
    import grain.python as pygrain
    import jax

    spi = getattr(dataset, "samples_per_instance", 1)
    if batch_size % spi != 0:
        raise ValueError(
            f"batch_size {batch_size} not divisible by "
            f"samples_per_instance {spi}")

    shard_index = jax.process_index() if shard_index is None else shard_index
    shard_count = jax.process_count() if shard_count is None else shard_count

    ds_ref = dataset

    # Fault wrapper: corrupt records are quarantined-and-redrawn inside
    # the worker (SRNDataset.safe_*) instead of killing the worker pool.
    # Duck-typed so non-SRN datasets without safe_* still work.
    fetch_pair = getattr(ds_ref, "safe_pair", ds_ref.pair)
    fetch_samples = getattr(ds_ref, "safe_samples", None) or ds_ref.samples

    class PairTransform(pygrain.RandomMapTransform):
        def random_map(self, idx, rng: np.random.Generator):
            return fetch_pair(int(idx), rng, num_cond=num_cond)

    class GroupTransform(pygrain.RandomMapTransform):
        def random_map(self, idx, rng: np.random.Generator):
            records = fetch_samples(int(idx), rng, num_cond=num_cond)
            return {k: np.stack([r[k] for r in records])
                    for k in records[0]}

    class FlattenGroups(pygrain.MapTransform):
        def map(self, batch: dict) -> dict:
            # (draws, spi, ...) -> (draws*spi, ...): groups stay
            # consecutive in the flattened batch.
            return {k: v.reshape((-1,) + v.shape[2:])
                    for k, v in batch.items()}

    operations = [
        PairTransform() if spi == 1 else GroupTransform(),
        pygrain.Batch(batch_size=batch_size // spi,
                      drop_remainder=drop_remainder),
    ]
    if spi > 1:
        operations.append(FlattenGroups())

    sampler = pygrain.IndexSampler(
        num_records=len(dataset),
        shard_options=pygrain.ShardOptions(
            shard_index=shard_index, shard_count=shard_count,
            drop_remainder=True),
        shuffle=True,
        num_epochs=num_epochs,
        seed=seed,
    )
    return pygrain.DataLoader(
        data_source=_PairSource(dataset),
        sampler=sampler,
        operations=operations,
        worker_count=num_workers,
    )


# ---------------------------------------------------------------------------
# In-process fallback iterator (tests, debugging, tiny datasets)
# ---------------------------------------------------------------------------
def iter_batches(dataset: SRNDataset, batch_size: int, *, seed: int = 0,
                 shard_index: int = 0, shard_count: int = 1,
                 num_cond: int = 1) -> Iterator[dict]:
    """Infinite shuffled batch iterator without worker processes.

    With dataset.samples_per_instance > 1 each index draw contributes that
    many consecutive batch slots from ONE instance (reference
    data_loader.py:183-195 semantics, where the torch collate flattens the
    per-item observation list); batch_size still counts MODEL samples, so
    it must be a multiple of samples_per_instance.
    """
    rng = np.random.default_rng(seed + shard_index)
    spi = getattr(dataset, "samples_per_instance", 1)
    if batch_size % spi != 0:
        raise ValueError(
            f"batch_size {batch_size} not divisible by "
            f"samples_per_instance {spi}")
    draws = batch_size // spi
    n = len(dataset)
    local = np.arange(shard_index, n, shard_count)
    if len(local) < draws:
        # Drop-last semantics (matching the Grain path and the reference's
        # DataLoader(drop_last=True)) would yield ZERO batches here; without
        # this check the while-True below would spin forever producing
        # nothing — a silent 100%-CPU hang instead of an error.
        raise ValueError(
            f"dataset shard has {len(local)} records but the batch needs "
            f"{draws} index draws — with drop-last batching no batch can "
            "ever be formed; lower train.batch_size or provide more data")
    # Fault wrapper (duck-typed: any dataset exposing .pair() works here;
    # SRNDataset's safe_* variants add quarantine-and-redraw on top).
    fetch_pair = getattr(dataset, "safe_pair", dataset.pair)
    fetch_samples = (getattr(dataset, "safe_samples", None)
                     or getattr(dataset, "samples", None))
    while True:
        order = rng.permutation(local)
        for start in range(0, len(order) - draws + 1, draws):
            if spi == 1:
                records = [fetch_pair(int(i), rng, num_cond=num_cond)
                           for i in order[start:start + draws]]
            else:
                records = [r for i in order[start:start + draws]
                           for r in fetch_samples(int(i), rng,
                                                  num_cond=num_cond)]
            yield {k: np.stack([r[k] for r in records]) for k in records[0]}


def cycle(loader) -> Iterator[dict]:
    """Loop a (possibly finite) loader forever (reference train.py:18-21)."""
    while True:
        count = 0
        for item in loader:
            count += 1
            yield item
        if count == 0:
            raise RuntimeError("empty data loader")
