"""Corpus mixer: weighted sampling across N named packed corpora.

ROADMAP item 5's data plane. One training run draws each batch slot from
one of N `nvs3d pack` corpora ("cars", "chairs", ...) with probability
weight/Σweights, while keeping every contract the single-corpus packed
path earned:

  - ONE sequential rng drives everything (the per-slot corpus draw AND
    the per-corpus shuffle epochs), so the stream is deterministic in
    (seed, shard_index) and stable across restarts — and a ONE-corpus
    mix consumes the rng exactly like the plain packed loader, making it
    BIT-IDENTICAL to `backend='packed'` without a mix (tested);
  - the plan/assemble split survives: the mixer loader plans on the
    coordinator thread and decodes on the PipelinedLoader worker pool,
    so mixing never stalls the step loop (MinatoLoader's rule);
  - quarantine stays per-corpus: a corrupt record costs one record of
    ONE corpus, fault substitutes are redrawn WITHIN the same corpus
    (per-corpus loss attribution stays honest), and per-corpus
    quarantine/decode-error stats publish as nvs3d_corpus_* gauges.

Batch records gain two int32 fields:
  corpus_id  — position of the owning corpus in the mix spec; the train
               step segment-sums per-sample losses by it (per-corpus
               loss attribution in metrics.csv/telemetry.jsonl);
  category   — scene-category id for conditioning (ConditioningProcessor
               category table, model.num_classes). Defaults to the
               corpus position; a corpus whose packed metadata carries a
               class vocab still maps to one category per corpus (the
               mix is the category vocabulary).

Resolution safety: a corpus packed from images NATIVELY smaller than the
requested training sidelength would silently upsample — at a 128 ladder
rung that poisons the high-res phase with blurry 64px data. The mixer
reads each corpus's index.json `meta` block (written by `nvs3d pack`)
and REFUSES a resolution-mismatched corpus with an error naming it.
Corpora packed before the meta block existed skip the check (nothing to
cross-check against).
"""

from __future__ import annotations

import dataclasses
import json
import os
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from novel_view_synthesis_3d_tpu.data.pipeline import PipelinedLoader
from novel_view_synthesis_3d_tpu.data.records import (
    INDEX_NAME,
    PackedDataset,
)


@dataclasses.dataclass(frozen=True)
class CorpusSpec:
    """One `name:weight:path` entry of a data.mix string."""

    name: str
    weight: float
    path: str


def parse_mix_spec(spec: str) -> List[CorpusSpec]:
    """data.mix string → ordered CorpusSpec list.

    Config.validate() already rejects malformed specs loudly at startup;
    this re-raises on the same conditions so direct callers (tools,
    tests) get the same contract.
    """
    out: List[CorpusSpec] = []
    seen = set()
    for entry in spec.split(","):
        parts = entry.strip().split(":", 2)
        if len(parts) != 3 or not all(p.strip() for p in parts):
            raise ValueError(
                f"mix entry {entry.strip()!r} must be 'name:weight:path'")
        name, weight, path = (p.strip() for p in parts)
        if name in seen:
            raise ValueError(f"mix names corpus {name!r} twice")
        seen.add(name)
        w = float(weight)
        if w <= 0:
            raise ValueError(
                f"mix corpus {name!r} weight must be > 0, got {w}")
        out.append(CorpusSpec(name=name, weight=w, path=path))
    if not out:
        raise ValueError("empty mix spec")
    return out


def corpus_meta(root_dir: str) -> Optional[dict]:
    """The `meta` block of a packed corpus's index.json, or None when
    absent (corpus packed before `nvs3d pack` wrote metadata)."""
    try:
        with open(os.path.join(root_dir, INDEX_NAME)) as fh:
            return json.load(fh).get("meta")
    except (OSError, json.JSONDecodeError):
        return None


def check_corpus_resolution(name: str, root_dir: str,
                            img_sidelength: int) -> None:
    """Refuse a corpus whose native capture resolution is below the
    requested training sidelength (loud, naming the corpus) — the
    resolution-ladder guard: a 64px-native corpus must not silently
    upsample into a 128 rung."""
    meta = corpus_meta(root_dir)
    if meta is None or not meta.get("resolution"):
        return  # pre-metadata corpus: nothing to cross-check
    native = int(meta["resolution"])
    if img_sidelength > native:
        raise ValueError(
            f"corpus {name!r} ({root_dir}) has native resolution "
            f"{native} but the run (ladder rung) wants img_sidelength="
            f"{img_sidelength} — training would silently UPSAMPLE this "
            "corpus; drop it from data.mix at this rung or repack it "
            "from higher-resolution sources")


class MixedDataset:
    """N packed corpora behind one FlatViewDataset-shaped surface.

    Flat indices are the concatenation of the member corpora's index
    spaces (corpus c owns [base[c], base[c+1])); plan/assemble/quarantine
    delegate to the owning PackedDataset with index translation, so every
    packed-plane behavior (shard re-hash at open, scene LRU, record
    quarantine) applies unchanged per corpus. Assembled records gain the
    mixer's `corpus_id` and `category` int32 fields.
    """

    def __init__(self, specs: Sequence[CorpusSpec],
                 datasets: Sequence[PackedDataset]):
        if len(specs) != len(datasets) or not specs:
            raise ValueError("specs and datasets must align and be "
                             "non-empty")
        self.specs = list(specs)
        self.datasets = list(datasets)
        spis = {ds.samples_per_instance for ds in datasets}
        if len(spis) != 1:
            raise ValueError(
                f"mixed corpora disagree on samples_per_instance: {spis}")
        self.samples_per_instance = spis.pop()
        self.max_record_retries = max(ds.max_record_retries
                                      for ds in datasets)
        self.root_dir = "mix(" + ",".join(
            f"{s.name}:{s.path}" for s in specs) + ")"
        self._bases = np.concatenate(
            [[0], np.cumsum([len(ds) for ds in datasets])])
        w = np.asarray([s.weight for s in specs], dtype=np.float64)
        self.weights = w / w.sum()
        self.decode_errors = [0] * len(specs)
        self._publish_gauges()

    # -- index space ----------------------------------------------------
    def __len__(self) -> int:
        return int(self._bases[-1])

    def corpus_of(self, flat_idx: int) -> int:
        c = int(np.searchsorted(self._bases, flat_idx, side="right") - 1)
        if not 0 <= c < len(self.datasets):
            raise IndexError(f"flat index {flat_idx} outside the mix "
                             f"(len {len(self)})")
        return c

    def corpus_range(self, c: int) -> Tuple[int, int]:
        return int(self._bases[c]), int(self._bases[c + 1])

    def locate_corpus(self, flat_idx: int) -> Tuple[int, int]:
        c = self.corpus_of(flat_idx)
        return c, int(flat_idx - self._bases[c])

    @property
    def quarantined(self) -> set:
        """Union of the member corpora's quarantine sets, globalized.
        Live view — the loaders only do membership tests and len()."""
        out: set = set()
        for c, ds in enumerate(self.datasets):
            base = int(self._bases[c])
            out.update(base + i for i in ds.quarantined)
        return out

    def live_indices(self) -> np.ndarray:
        return np.concatenate([
            int(self._bases[c]) + ds.live_indices()
            for c, ds in enumerate(self.datasets)])

    def live_indices_of(self, c: int) -> np.ndarray:
        return int(self._bases[c]) + self.datasets[c].live_indices()

    # -- plan/assemble delegation (index + exception translation) -------
    def _globalize(self, exc: Exception, c: int) -> None:
        flat = getattr(exc, "flat_index", None)
        if flat is not None:
            exc.flat_index = int(self._bases[c]) + int(flat)

    def _plan_pair(self, flat_idx: int, rng: np.random.Generator,
                   num_cond: int = 1) -> tuple:
        c, local = self.locate_corpus(flat_idx)
        try:
            return (c, self.datasets[c]._plan_pair(local, rng,
                                                   num_cond=num_cond))
        except Exception as exc:
            self._globalize(exc, c)
            raise

    def _plan_samples(self, flat_idx: int, rng: np.random.Generator,
                      num_cond: int = 1) -> List[tuple]:
        c, local = self.locate_corpus(flat_idx)
        try:
            plans = self.datasets[c]._plan_samples(local, rng,
                                                   num_cond=num_cond)
        except Exception as exc:
            self._globalize(exc, c)
            raise
        return [(c, p) for p in plans]

    def _assemble_pair(self, plan: tuple) -> dict:
        c, sub_plan = plan
        try:
            rec = self.datasets[c]._assemble_pair(sub_plan)
        except Exception as exc:
            self._globalize(exc, c)
            raise
        rec["corpus_id"] = np.int32(c)
        rec["category"] = np.int32(c)
        return rec

    def pair(self, flat_idx: int, rng: np.random.Generator,
             num_cond: int = 1) -> dict:
        return self._assemble_pair(
            self._plan_pair(flat_idx, rng, num_cond=num_cond))

    def _quarantine(self, flat_idx: int, exc: Exception) -> None:
        c, local = self.locate_corpus(flat_idx)
        self.datasets[c]._quarantine(local, exc)
        self.decode_errors[c] += 1
        self._publish_gauges()

    # -- per-corpus stats ----------------------------------------------
    def corpus_stats(self) -> List[dict]:
        """One dict per corpus: identity, weight, and quarantine health —
        the rows the trainer lands in telemetry.jsonl via the bus."""
        out = []
        for c, (spec, ds) in enumerate(zip(self.specs, self.datasets)):
            out.append({
                "corpus": spec.name,
                "corpus_id": c,
                "weight": float(self.weights[c]),
                "records": len(ds),
                "quarantined": len(ds.quarantined),
                "decode_errors": self.decode_errors[c],
                "shards_open": getattr(ds, "shards_open", None),
                "shards_quarantined": getattr(ds, "shards_quarantined",
                                              None),
            })
        return out

    def _publish_gauges(self) -> None:
        """nvs3d_corpus_* gauges: per-corpus quarantine/decode health on
        the shared obs registry, next to the packed plane's shard
        gauges."""
        try:
            from novel_view_synthesis_3d_tpu import obs

            reg = obs.get_registry()
            for c, (spec, ds) in enumerate(zip(self.specs,
                                               self.datasets)):
                reg.gauge(
                    f"nvs3d_corpus_{spec.name}_records",
                    f"records corpus {spec.name!r} serves").set(len(ds))
                reg.gauge(
                    f"nvs3d_corpus_{spec.name}_quarantined",
                    f"records of corpus {spec.name!r} quarantined by "
                    "the fault ladder").set(len(ds.quarantined))
                reg.gauge(
                    f"nvs3d_corpus_{spec.name}_decode_errors",
                    f"decode errors charged to corpus "
                    f"{spec.name!r}").set(self.decode_errors[c])
        except Exception:
            pass  # telemetry must never fail the data path


class MixedLoader(PipelinedLoader):
    """PipelinedLoader whose plan stream draws each batch slot's corpus
    first (one rng.choice per batch from the SINGLE sequential rng),
    then pulls the slot's index from that corpus's own permutation
    epoch — replenished from the same rng, in draw order.

    With ONE corpus the override defers to the base per-epoch
    permutation stream verbatim: rng consumption is identical to the
    plain packed loader, so a one-corpus mix is bit-identical to
    `backend='packed'` (tests/test_corpus.py asserts it).

    Fault substitutes are redrawn WITHIN the failed slot's corpus (from
    the dedicated redraw rng) — substitution must not shift loss/
    quarantine attribution across corpora.
    """

    def __init__(self, dataset: MixedDataset, batch_size: int, *,
                 seed: int = 0, shard_index: int = 0, num_cond: int = 1,
                 workers: int = 4, depth: int = 2,
                 skip_batches: int = 0):
        self._mix = dataset
        self.corpus_draws = [0] * len(dataset.datasets)
        super().__init__(dataset, batch_size, seed=seed,
                         shard_index=shard_index, num_cond=num_cond,
                         workers=workers, depth=depth,
                         skip_batches=skip_batches)

    def _plan_stream(self):
        mix = self._mix
        n = len(mix.datasets)
        if n == 1:
            # One corpus: the base stream IS the mixer stream — same rng
            # calls in the same order as the plain packed loader.
            yield from super()._plan_stream()
            return
        queues: List[deque] = [deque() for _ in range(n)]
        while True:
            cids = self._rng.choice(n, size=self._draws, p=mix.weights)
            idxs = []
            for c in cids:
                c = int(c)
                if not queues[c]:
                    queues[c].extend(
                        int(i) for i in self._rng.permutation(
                            mix.live_indices_of(c)))
                idxs.append(queues[c].popleft())
                self.corpus_draws[c] += 1
            yield idxs

    # -- corpus-confined fault substitution -----------------------------
    def _plan_draw_safe(self, flat_idx: int) -> list:
        if flat_idx not in self._ds.quarantined:
            try:
                return self._plan_draw(flat_idx, self._rng)
            except Exception as exc:
                self._ds._quarantine(
                    getattr(exc, "flat_index", flat_idx), exc)
        return self._substitute_plan(
            corpus=self._mix.corpus_of(flat_idx))[1]

    def _substitute_plan(self, corpus: Optional[int] = None) -> tuple:
        if corpus is None:
            return super()._substitute_plan()
        lo, hi = self._mix.corpus_range(corpus)
        quarantined = self._ds.quarantined
        for _ in range(self._ds.max_record_retries + 1):
            j = lo + int(self._redraw_rng.integers(hi - lo))
            if j in quarantined:
                quarantined = self._ds.quarantined  # refresh the view
                continue
            try:
                return j, self._plan_draw(j, self._redraw_rng)
            except Exception as exc:
                self._ds._quarantine(getattr(exc, "flat_index", j), exc)
                quarantined = self._ds.quarantined
        name = self._mix.specs[corpus].name
        raise RuntimeError(
            f"data: {self._ds.max_record_retries + 1} consecutive "
            f"substitute draws inside corpus {name!r} failed or were "
            f"quarantined ({len(self._mix.datasets[corpus].quarantined)} "
            f"quarantined in that corpus) — the corpus is too corrupt "
            "to keep training; see the quarantine reports above")

    def _substitute_decoded(self, flat_idx: int, exc: Exception) -> list:
        corpus = self._mix.corpus_of(
            int(getattr(exc, "flat_index", flat_idx)))
        self._ds._quarantine(getattr(exc, "flat_index", flat_idx), exc)
        if self._c_decode_errors is not None:
            self._c_decode_errors.inc()
        last: Exception = exc
        for _ in range(self._ds.max_record_retries + 1):
            sub_idx, plans = self._substitute_plan(corpus=corpus)
            try:
                return self._decode_draw(plans)
            except Exception as exc2:
                self._ds._quarantine(
                    getattr(exc2, "flat_index", sub_idx), exc2)
                last = exc2
        name = self._mix.specs[corpus].name
        raise RuntimeError(
            f"data: substitute decodes inside corpus {name!r} kept "
            f"failing — the corpus is too corrupt to keep training; "
            f"last error: {last}")


def make_mixed_dataset(cfg, *, shard_index: int = 0,
                       shard_count: int = 1) -> MixedDataset:
    """MixedDataset from a DataConfig with data.mix set.

    Each corpus is a full PackedDataset (per-host shard slice, open-time
    re-hash, scene cache) built with the shared DataConfig knobs;
    check_corpus_resolution refuses any corpus whose packed metadata
    says it cannot honestly serve cfg.img_sidelength.
    """
    specs = parse_mix_spec(cfg.mix)
    datasets = []
    for spec in specs:
        check_corpus_resolution(spec.name, spec.path, cfg.img_sidelength)
        datasets.append(PackedDataset(
            root_dir=spec.path,
            img_sidelength=cfg.img_sidelength,
            max_num_instances=cfg.max_num_instances,
            max_observations_per_instance=(
                cfg.max_observations_per_instance),
            specific_observation_idcs=cfg.specific_observation_idcs,
            samples_per_instance=cfg.samples_per_instance,
            max_record_retries=cfg.max_record_retries,
            shard_index=shard_index,
            shard_count=shard_count,
        ))
    return MixedDataset(specs, datasets)


def make_mixed_loader(dataset: MixedDataset, batch_size: int, *,
                      seed: int = 0, shard_index: int = 0,
                      num_cond: int = 1, workers: int = 4,
                      depth: int = 2, skip_batches: int = 0) -> MixedLoader:
    """Compute-overlapped mixer loader (`data.mix` non-empty)."""
    return MixedLoader(dataset, batch_size, seed=seed,
                       shard_index=shard_index, num_cond=num_cond,
                       workers=workers, depth=depth,
                       skip_batches=skip_batches)
