"""ctypes bindings for the native IO runtime (native/libnvs3d_io.so).

The C++ library replaces the reference's native data-path dependencies
(SURVEY.md §2.4: torch DataLoader workers, OpenCV resize, imageio decode)
with a first-party host runtime: zlib PNG decode, area resize, SRN text
parsers, and a threaded shuffling prefetch loader.

Everything here degrades gracefully: if the shared library is missing it is
built on demand with `make`; if that fails, `available()` returns False and
callers fall back to the pure-Python path (data/srn.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libnvs3d_io.so")

_lib = None
_lib_lock = threading.Lock()
_load_failed = False

# Must match NVS3D_ABI_VERSION in native/include/nvs3d_io.h: the binding
# refuses to drive a stale .so whose signatures may have changed.
_ABI_VERSION = 3


def _build() -> bool:
    try:
        # Always invoke make: it is an mtime-based no-op when the library is
        # current, and it REBUILDS a stale .so left over from older sources
        # (an .so-exists check alone would load mismatched signatures).
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except Exception as exc:
        if os.path.exists(_LIB_PATH):
            # The ABI gate below catches signature changes, but a stale
            # binary with the same ABI number (behavior change only) would
            # load silently — say so, so drift is diagnosable.
            import warnings
            warnings.warn(
                f"native IO: `make` failed ({exc!r}); falling back to the "
                f"pre-existing {_LIB_PATH}, which may be stale")
            return True
        return False


def _load():
    global _lib, _load_failed
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        if not _build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _load_failed = True
            return None
        try:
            lib.nvs3d_abi_version.restype = ctypes.c_int
            abi = int(lib.nvs3d_abi_version())
        except AttributeError:
            abi = -1  # pre-versioning build
        if abi != _ABI_VERSION:
            # A stale library is already mapped into this process; dlopen
            # would keep returning it. Fall back to the Python/grain path.
            _load_failed = True
            return None
        c_char_pp = ctypes.POINTER(ctypes.c_char_p)
        f32_p = ctypes.POINTER(ctypes.c_float)
        i32_p = ctypes.POINTER(ctypes.c_int32)

        lib.nvs3d_last_error.restype = ctypes.c_char_p
        lib.nvs3d_decode_png_rgb.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t]
        lib.nvs3d_load_rgb.argtypes = [ctypes.c_char_p, ctypes.c_int, f32_p]
        lib.nvs3d_load_rgb_batch.argtypes = [
            c_char_pp, ctypes.c_int, ctypes.c_int, ctypes.c_int, f32_p]
        lib.nvs3d_parse_pose.argtypes = [ctypes.c_char_p, f32_p]
        lib.nvs3d_parse_intrinsics.argtypes = [
            ctypes.c_char_p, ctypes.c_int, f32_p, f32_p, f32_p,
            ctypes.POINTER(ctypes.c_int)]
        lib.nvs3d_loader_create.restype = ctypes.c_void_p
        lib.nvs3d_loader_create.argtypes = [
            c_char_pp, c_char_pp, i32_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int, ctypes.c_int]
        lib.nvs3d_loader_next.argtypes = [
            ctypes.c_void_p, f32_p, f32_p, f32_p, f32_p, i32_p]
        lib.nvs3d_loader_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _err(lib) -> str:
    return lib.nvs3d_last_error().decode("utf-8", "replace")


def _paths_array(paths: Sequence[str]):
    arr = (ctypes.c_char_p * len(paths))()
    arr[:] = [p.encode() for p in paths]
    return arr


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def load_rgb(path: str, sidelength: int) -> np.ndarray:
    """Native load_rgb → (S, S, 3) float32 in [-1, 1]."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native IO library unavailable")
    out = np.empty((sidelength, sidelength, 3), dtype=np.float32)
    if lib.nvs3d_load_rgb(path.encode(), sidelength, _f32p(out)):
        raise RuntimeError(f"nvs3d_load_rgb: {_err(lib)}")
    return out


def load_rgb_batch(paths: Sequence[str], sidelength: int,
                   n_threads: int = 8) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("native IO library unavailable")
    out = np.empty((len(paths), sidelength, sidelength, 3), dtype=np.float32)
    if lib.nvs3d_load_rgb_batch(_paths_array(paths), len(paths), sidelength,
                                n_threads, _f32p(out)):
        raise RuntimeError(f"nvs3d_load_rgb_batch: {_err(lib)}")
    return out


def parse_pose(path: str) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("native IO library unavailable")
    out = np.empty(16, dtype=np.float32)
    if lib.nvs3d_parse_pose(path.encode(), _f32p(out)):
        raise RuntimeError(f"nvs3d_parse_pose: {_err(lib)}")
    return out.reshape(4, 4)


def parse_intrinsics(path: str, sidelength: Optional[int] = None
                     ) -> Tuple[np.ndarray, np.ndarray, float, bool]:
    lib = _load()
    if lib is None:
        raise RuntimeError("native IO library unavailable")
    K = np.empty(9, dtype=np.float32)
    bary = np.empty(3, dtype=np.float32)
    scale = ctypes.c_float()
    w2c = ctypes.c_int()
    if lib.nvs3d_parse_intrinsics(path.encode(),
                                  sidelength if sidelength else 0,
                                  _f32p(K), _f32p(bary),
                                  ctypes.byref(scale), ctypes.byref(w2c)):
        raise RuntimeError(f"nvs3d_parse_intrinsics: {_err(lib)}")
    return K.reshape(3, 3), bary, float(scale.value), bool(w2c.value)


class NativePairLoader:
    """Threaded shuffling pair loader backed by the C++ runtime.

    Yields the same batch dict as data/pipeline.iter_batches — clean image
    pairs + 4×4 poses decomposed into R/t, plus per-record intrinsics —
    but with decode, shuffle, pairing, and prefetch all in native worker
    threads (the reference's torch-DataLoader role, train.py:108-113).
    """

    def __init__(self, rgb_paths: Sequence[str], pose_paths: Sequence[str],
                 instance_ids: Sequence[int], Ks: np.ndarray, *,
                 sidelength: int, batch_size: int, num_cond: int = 1,
                 samples_per_instance: int = 1, n_threads: int = 8,
                 prefetch_depth: int = 4, seed: int = 0,
                 shard_index: int = 0, shard_count: int = 1):
        lib = _load()
        if lib is None:
            raise RuntimeError("native IO library unavailable")
        assert len(rgb_paths) == len(pose_paths) == len(instance_ids)
        self._lib = lib
        self._B = batch_size
        self._S = sidelength
        self._K_frames = num_cond
        # Keep path arrays alive for the loader's lifetime (the C++ side
        # copies at create time, but be conservative about GC ordering).
        self._rgb_arr = _paths_array(rgb_paths)
        self._pose_arr = _paths_array(pose_paths)
        inst = np.ascontiguousarray(np.asarray(instance_ids, dtype=np.int32))
        self._inst = inst
        self._Ks = np.asarray(Ks, dtype=np.float32)  # (n_records, 3, 3)
        assert self._Ks.shape == (len(rgb_paths), 3, 3)
        self._handle = lib.nvs3d_loader_create(
            self._rgb_arr, self._pose_arr,
            inst.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(rgb_paths), sidelength, batch_size, num_cond,
            samples_per_instance, n_threads,
            prefetch_depth, seed, shard_index, shard_count)
        if not self._handle:
            raise RuntimeError(f"nvs3d_loader_create: {_err(lib)}")

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        B, S, F = self._B, self._S, self._K_frames
        x = np.empty((B, F, S, S, 3), dtype=np.float32)
        target = np.empty((B, S, S, 3), dtype=np.float32)
        pose1 = np.empty((B, F, 4, 4), dtype=np.float32)
        pose2 = np.empty((B, 4, 4), dtype=np.float32)
        idx = np.empty((B,), dtype=np.int32)
        rc = self._lib.nvs3d_loader_next(
            self._handle, _f32p(x), _f32p(target), _f32p(pose1), _f32p(pose2),
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc:
            raise RuntimeError(f"nvs3d_loader_next: {_err(self._lib)}")
        if F == 1:  # same per-record contract as SRNDataset.pair(num_cond=1)
            x, pose1 = x[:, 0], pose1[:, 0]
            R1, t1 = pose1[:, :3, :3], pose1[:, :3, 3]
        else:
            R1, t1 = pose1[:, :, :3, :3], pose1[:, :, :3, 3]
        return {
            "x": x,
            "target": target,
            "R1": R1.copy(),
            "t1": t1.copy(),
            "R2": pose2[:, :3, :3].copy(),
            "t2": pose2[:, :3, 3].copy(),
            "K": self._Ks[idx],
        }

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.nvs3d_loader_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


class FaultTolerantNativeLoader:
    """Quarantine-and-rebuild wrapper around NativePairLoader.

    A corrupt record stops the whole C++ worker pool (the loader's error
    contract), so recovery happens here: the worker tags its error with
    the failing file path, the wrapper quarantines every record touching
    that path and rebuilds the native loader without them — the same
    skipped-and-reported semantics as SRNDataset.safe_pair on the python/
    Grain backends. Bounded by `max_record_retries` consecutive rebuilds
    (reset on each successful batch), then the original error re-raises.
    """

    def __init__(self, build, rgb_paths: Sequence[str],
                 pose_paths: Sequence[str], instance_ids: Sequence[int],
                 Ks: np.ndarray, max_record_retries: int = 3):
        # `build` maps the (possibly filtered) record lists to a fresh
        # NativePairLoader; rebuilt after each quarantine.
        self._build = build
        self._records = list(zip(rgb_paths, pose_paths, instance_ids, Ks))
        self._retries = max_record_retries
        self.quarantined: List[str] = []
        self.fault_reports: List[dict] = []
        self._loader = self._make()

    def _make(self):
        rgb, pose, inst, Ks = zip(*self._records)
        # Compact the instance ids: quarantining can empty an instance,
        # and the C++ loader rejects id gaps ("instance with no
        # observations"). Grouping only needs ids to be consistent.
        remap: dict = {}
        inst = [remap.setdefault(i, len(remap)) for i in inst]
        return self._build(list(rgb), list(pose), inst, np.stack(Ks))

    def _quarantine_path(self, msg: str) -> bool:
        bad = [i for i, (r, p, _, _) in enumerate(self._records)
               if r in msg or p in msg]
        if not bad:
            return False
        for i in bad:
            path = self._records[i][0]
            self.quarantined.append(path)
            self.fault_reports.append({"path": path, "error": msg})
        self._records = [rec for i, rec in enumerate(self._records)
                         if i not in set(bad)]
        import sys

        print(f"warning: data fault (native loader): {msg!r} — "
              f"{len(bad)} record(s) quarantined, loader rebuilt "
              f"({len(self._records)} records remain)",
              file=sys.stderr, flush=True)
        return True

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        failures = 0
        while True:
            try:
                batch = next(self._loader)
                return batch
            except RuntimeError as exc:
                failures += 1
                if failures > self._retries or not self._records:
                    raise
                self._loader.close()
                if not self._quarantine_path(str(exc)):
                    raise  # not a record-level fault (e.g. tiny dataset)
                self._loader = self._make()

    def close(self) -> None:
        self._loader.close()


def make_native_loader(dataset, batch_size: int, *, num_cond: int = 1,
                       n_threads: int = 8,
                       prefetch_depth: int = 4, seed: int = 0,
                       shard_index: int = 0,
                       shard_count: int = 1,
                       max_record_retries: int = 3):
    """Build a (fault-tolerant) native loader from a data/srn.SRNDataset.

    dataset.samples_per_instance > 1 applies the reference's
    instance-grouped batching (data_loader.py:183-195) inside the C++
    loader: each shuffled index draw fills that many consecutive batch
    slots from one instance — same record semantics as
    pipeline.iter_batches' grouped path.
    """
    rgb: List[str] = []
    pose: List[str] = []
    inst: List[int] = []
    Ks: List[np.ndarray] = []
    for i, instance in enumerate(dataset.instances):
        for c, p in zip(instance.color_paths, instance.pose_paths):
            rgb.append(c)
            pose.append(p)
            inst.append(i)
            Ks.append(instance.K)

    def build(rgb_l, pose_l, inst_l, Ks_arr):
        return NativePairLoader(
            rgb_l, pose_l, inst_l, Ks_arr,
            sidelength=dataset.img_sidelength,
            batch_size=batch_size, num_cond=num_cond,
            samples_per_instance=getattr(dataset, "samples_per_instance", 1),
            n_threads=n_threads,
            prefetch_depth=prefetch_depth, seed=seed,
            shard_index=shard_index, shard_count=shard_count)

    if max_record_retries <= 0:
        return build(rgb, pose, inst, np.stack(Ks))
    return FaultTolerantNativeLoader(
        build, rgb, pose, inst, np.stack(Ks),
        max_record_retries=max_record_retries)
