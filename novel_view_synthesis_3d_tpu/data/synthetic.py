"""Synthetic SRN-format dataset for tests and smoke training.

The reference has no test fixtures at all (SURVEY.md §4); this writes a tiny
but REAL SRN directory tree (rgb/ pose/ intrinsics.txt) whose images are a
deterministic function of the camera pose, so a model trained on it can
actually reduce loss and a restored pipeline reproduces identical records.
"""

from __future__ import annotations

import os

import numpy as np
from PIL import Image


def look_at_pose(cam_pos: np.ndarray, target: np.ndarray | None = None) -> np.ndarray:
    """cam→world 4×4 with -z... camera +z looking from cam_pos toward target."""
    target = np.zeros(3) if target is None else target
    fwd = target - cam_pos
    fwd = fwd / np.linalg.norm(fwd)
    up = np.array([0.0, 0.0, 1.0])
    right = np.cross(fwd, up)
    if np.linalg.norm(right) < 1e-6:
        right = np.array([1.0, 0.0, 0.0])
    right = right / np.linalg.norm(right)
    down = np.cross(fwd, right)
    pose = np.eye(4, dtype=np.float32)
    # columns: camera x (right), y (down), z (forward) in world coords
    pose[:3, 0] = right
    pose[:3, 1] = down
    pose[:3, 2] = fwd
    pose[:3, 3] = cam_pos
    return pose


def render_view(base_color: np.ndarray, azimuth: float, elevation: float,
                size: int) -> np.ndarray:
    """Cheap pose-dependent 'render': a colored blob whose position encodes
    the camera azimuth/elevation. uint8 HWC."""
    img = np.full((size, size, 3), 255, dtype=np.uint8)
    cx = int((np.cos(azimuth) * 0.3 + 0.5) * size)
    cy = int((np.sin(azimuth) * 0.3 + 0.5) * size)
    r = max(2, int(size * (0.15 + 0.05 * np.sin(elevation))))
    yy, xx = np.mgrid[0:size, 0:size]
    mask = (xx - cx) ** 2 + (yy - cy) ** 2 <= r * r
    img[mask] = (base_color * 255).astype(np.uint8)
    # gradient strip encoding azimuth for extra signal
    strip = (np.linspace(0, 1, size)[None, :, None] * base_color[None, None])
    img[: size // 8] = (strip[0, :, :] * 255).astype(np.uint8)[None]
    return img


def write_synthetic_srn(root: str, num_instances: int = 3,
                        views_per_instance: int = 6, image_size: int = 64,
                        focal: float | None = None,
                        seed: int = 0) -> str:
    """Create root/inst_XX/{rgb,pose,intrinsics.txt} in SRN format."""
    rng = np.random.default_rng(seed)
    focal = focal if focal is not None else image_size * 1.2
    for i in range(num_instances):
        inst = os.path.join(root, f"inst_{i:02d}")
        os.makedirs(os.path.join(inst, "rgb"), exist_ok=True)
        os.makedirs(os.path.join(inst, "pose"), exist_ok=True)
        base_color = rng.uniform(0.2, 1.0, size=3)
        with open(os.path.join(inst, "intrinsics.txt"), "w") as fh:
            fh.write(f"{focal} {image_size / 2} {image_size / 2} 0.\n")
            fh.write("0. 0. 0.\n")
            fh.write("1.\n")
            fh.write(f"{image_size} {image_size}\n")
        for v in range(views_per_instance):
            az = 2 * np.pi * v / views_per_instance
            el = 0.3 + 0.1 * np.sin(v)
            dist = 2.5
            cam = np.array([
                dist * np.cos(az) * np.cos(el),
                dist * np.sin(az) * np.cos(el),
                dist * np.sin(el),
            ])
            pose = look_at_pose(cam)
            img = render_view(base_color, az, el, image_size)
            Image.fromarray(img).save(os.path.join(inst, "rgb", f"{v:06d}.png"))
            # alternate between 4×4 and flat-16 layouts to exercise both parsers
            path = os.path.join(inst, "pose", f"{v:06d}.txt")
            if v % 2 == 0:
                np.savetxt(path, pose, fmt="%.8f")
            else:
                with open(path, "w") as fh:
                    fh.write(" ".join(f"{x:.8f}" for x in pose.reshape(-1)))
    return root


def make_example_batch(batch_size: int = 2, sidelength: int = 64,
                       num_cond: int = 1,
                       seed: int = 0) -> dict:
    """In-memory random batch with geometrically valid poses — the analogue
    of the reference's `create_sample_data` (train.py:23-34) but with real
    rotation matrices and intrinsics, shaped for the train step."""
    rng = np.random.default_rng(seed)

    def pose():
        az = rng.uniform(0, 2 * np.pi)
        cam = np.array([2.5 * np.cos(az), 2.5 * np.sin(az), 1.0])
        return look_at_pose(cam)

    f = sidelength * 1.2
    K = np.array([[f, 0, sidelength / 2], [0, f, sidelength / 2], [0, 0, 1]],
                 dtype=np.float32)
    poses1 = np.stack([
        np.stack([pose() for _ in range(num_cond)]) for _ in range(batch_size)])
    poses2 = np.stack([pose() for _ in range(batch_size)])
    x = rng.uniform(-1, 1, (batch_size, num_cond, sidelength, sidelength, 3))
    if num_cond == 1:
        x = x[:, 0]
        R1 = poses1[:, 0, :3, :3]
        t1 = poses1[:, 0, :3, 3]
    else:
        R1 = poses1[:, :, :3, :3]
        t1 = poses1[:, :, :3, 3]
    return {
        "x": x.astype(np.float32),
        "target": rng.uniform(-1, 1, (batch_size, sidelength, sidelength, 3)).astype(np.float32),
        "R1": R1.astype(np.float32),
        "t1": t1.astype(np.float32),
        "R2": poses2[:, :3, :3].astype(np.float32),
        "t2": poses2[:, :3, 3].astype(np.float32),
        "K": np.broadcast_to(K, (batch_size, 3, 3)).copy(),
    }
