from novel_view_synthesis_3d_tpu.data.pipeline import (  # noqa: F401
    PipelinedLoader,
    cycle,
    iter_batches,
    make_dataset,
    make_grain_loader,
    make_packed_loader,
)
from novel_view_synthesis_3d_tpu.data.records import (  # noqa: F401
    PackedDataset,
    pack_srn,
    verify_packed,
)
from novel_view_synthesis_3d_tpu.data.srn import (  # noqa: F401
    FlatViewDataset,
    SRNDataset,
    SRNInstance,
    load_pose,
    load_rgb,
    parse_intrinsics,
)
from novel_view_synthesis_3d_tpu.data.synthetic import (  # noqa: F401
    make_example_batch,
    write_synthetic_srn,
)
