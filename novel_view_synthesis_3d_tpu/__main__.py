from novel_view_synthesis_3d_tpu.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
