"""Model lifecycle registry (docs/DESIGN.md "Model lifecycle").

Closes the trainer → production loop: the trainer PUBLISHES versioned,
content-hashed EMA snapshots (publisher.py → store.py) to the `latest`
channel; the quality GATE (gate.py) decides whether a candidate may
advance to `stable`; a serving process subscribed to a channel
(watcher.py) HOT-RELOADS the new weights with zero downtime
(sample/service.py swap path). `nvs3d registry
{list,publish,promote,rollback,gc}` are the operator verbs.

Event logging routes through novel_view_synthesis_3d_tpu.obs (the
EventBus is the single events.csv write path); this package never touches
the telemetry files itself.
"""

from novel_view_synthesis_3d_tpu.registry.gate import (  # noqa: F401
    GateMatrixResult,
    GateResult,
    decide,
    make_psnr_probe,
    make_trajectory_probe,
    promote,
    rollback,
    run_gate,
    run_gate_matrix,
)
from novel_view_synthesis_3d_tpu.registry.manifest import (  # noqa: F401
    PARAMS_FILE,
    VersionManifest,
    config_digest,
    version_id,
)
from novel_view_synthesis_3d_tpu.registry.publisher import (  # noqa: F401
    RegistryPublisher,
)
from novel_view_synthesis_3d_tpu.registry.store import (  # noqa: F401
    IntegrityError,
    RegistryError,
    RegistryStore,
)
from novel_view_synthesis_3d_tpu.registry.watcher import (  # noqa: F401
    RegistryWatcher,
)
