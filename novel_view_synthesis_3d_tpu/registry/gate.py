"""Quality gate: a version must not regress PSNR to reach `stable`.

The `latest` channel tracks training; `stable` is what production serves.
Between the two sits this gate: a FIXED-SEED PSNR probe (eval/metrics.py
math, a small respaced sampler) scored for the candidate AND the
incumbent stable version on the same conditioning batch and the same
noise, so the comparison isolates the weights. A candidate that regresses
beyond `registry.gate_margin_db` is refused — the stable pointer never
moves, a `gate_fail` row lands in the event log, and the operator's
rollback path (`nvs3d registry rollback`) stays one command away for
regressions the probe missed.

The probe is a tripwire, not a benchmark: a handful of rows at a few
reverse steps, sized to catch "the new checkpoint is broken" (NaN-poisoned
EMA, truncated payload, wrong lineage), not half-dB quality drift — the
full `eval` CLI remains the measurement instrument.

The probe scores candidates AT THE SERVING PRECISION
(`make_psnr_probe(precision=...)` = `serve.precision`): a bf16/int8
deployment's quantization loss is part of what ships, so it counts
against `registry.gate_margin_db` like any other regression.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from novel_view_synthesis_3d_tpu.registry.store import (
    RegistryError,
    RegistryStore,
)

# event_cb(step, kind, detail, model_version) — the EventBus-routed hook
# (novel_view_synthesis_3d_tpu.obs) callers wire in; None = silent.
EventCb = Callable[[int, str, str, str], None]


@dataclasses.dataclass(frozen=True)
class GateResult:
    passed: bool
    candidate: str
    incumbent: Optional[str]
    candidate_psnr: float
    incumbent_psnr: Optional[float]
    margin_db: float
    reason: str

    @property
    def delta_db(self) -> Optional[float]:
        if self.incumbent_psnr is None:
            return None
        return self.candidate_psnr - self.incumbent_psnr


def decide(candidate_psnr: float, incumbent_psnr: Optional[float],
           margin_db: float) -> tuple:
    """(passed, reason) for a candidate-vs-incumbent PSNR pair.

    No incumbent = pass (first promotion bootstraps the channel). A
    non-finite candidate PSNR always fails — that is the broken-payload
    signature the gate exists for."""
    if candidate_psnr != candidate_psnr:  # NaN
        return False, "candidate probe PSNR is non-finite"
    if incumbent_psnr is None:
        return True, "no incumbent: bootstrap promotion"
    delta = candidate_psnr - incumbent_psnr
    if delta >= -margin_db:
        return True, (f"probe delta {delta:+.2f} dB within margin "
                      f"{margin_db:.2f} dB")
    return False, (f"probe regression {delta:+.2f} dB exceeds margin "
                   f"{margin_db:.2f} dB")


def make_psnr_probe(model, diffusion, batch: dict, *,
                    sample_steps: int, seed: int = 0,
                    precision: str = "float32"):
    """probe(params) -> mean PSNR (dB) of sampled vs ground-truth targets.

    One jitted sampler closure serves both the candidate and the
    incumbent (params are an argument, so scoring two versions costs zero
    extra compiles — the same property the serving hot-swap leans on),
    and the fixed key means both see bit-identical noise.

    `precision` stages BOTH versions' weights exactly the way the
    serving path would (sample/precision.py: bf16 cast / weight-only
    int8 quantize→dequantize) before scoring, so quantization loss
    counts against the gate margin — a candidate that only looks good
    in f32 cannot be promoted into a bf16/int8 deployment. Pass the
    deployment's `serve.precision` here (the CLI promote path does)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from novel_view_synthesis_3d_tpu.diffusion.schedules import (
        sampling_schedule)
    from novel_view_synthesis_3d_tpu.eval.metrics import psnr
    from novel_view_synthesis_3d_tpu.sample import (
        precision as precision_lib)
    from novel_view_synthesis_3d_tpu.sample.ddpm import make_sampler

    precision_lib.validate_precision(precision)
    sampler = make_sampler(model, sampling_schedule(diffusion, sample_steps),
                           diffusion)
    cond = {k: jnp.asarray(batch[k])
            for k in ("x", "R1", "t1", "R2", "t2", "K")}
    truth = np.asarray(batch["target"])
    key = jax.random.PRNGKey(seed)

    def stage(params):
        staged = precision_lib.stage_params(params, precision)
        if precision == "int8":
            # Dequantize eagerly: the probe measures the NUMERICAL
            # effect of serving at int8 (the dequantized bf16 weights
            # are bit-identical to what the serving program computes
            # with), not the memory layout.
            staged = precision_lib.make_resolver("int8")(staged)
        return staged

    def probe(params) -> float:
        imgs = np.asarray(jax.device_get(
            sampler(stage(params), key, cond)))
        return float(np.mean(np.asarray(psnr(imgs, truth))))

    return probe


def make_trajectory_probe(model, diffusion, batch: dict, *,
                          frames: int, sample_steps: int, seed: int = 0,
                          precision: str = "float32",
                          k_max: Optional[int] = None):
    """probe(params) -> mean adjacent-frame PSNR (dB) over a fixed orbit.

    The multi-view CONSISTENCY tripwire
    (eval/metrics.multi_view_consistency): the candidate autoregressively
    renders a fixed-seed orbit with stochastic conditioning — each frame
    conditions on a random previously generated view, exactly the
    trajectory-serving workload — and is scored on how well adjacent
    frames agree. A distilled or quantized model whose SINGLE frames
    look fine but whose orbit drifts (the failure mode few-step
    students are prone to) regresses here, so pairing this probe with
    `make_psnr_probe` under the same `registry.gate_margin_db` gates
    promotions on trajectory quality, not just single-frame PSNR.
    Deterministic: fixed key, fixed orbit poses (camera radius taken
    from the probe batch), identical noise for candidate and incumbent.
    `precision` stages weights exactly like the serving path, as in
    `make_psnr_probe`."""
    import jax
    import numpy as np

    from novel_view_synthesis_3d_tpu.diffusion.schedules import (
        sampling_schedule)
    from novel_view_synthesis_3d_tpu.eval.metrics import adjacent_psnr
    from novel_view_synthesis_3d_tpu.sample import (
        precision as precision_lib)
    from novel_view_synthesis_3d_tpu.sample.ddpm import (
        autoregressive_generate, make_stochastic_sampler)
    from novel_view_synthesis_3d_tpu.utils.geometry import orbit_poses

    if frames < 2:
        raise ValueError(
            f"trajectory probe needs frames >= 2 (adjacent pairs), "
            f"got {frames}")
    precision_lib.validate_precision(precision)
    schedule = sampling_schedule(diffusion, sample_steps)
    first_view = {
        "x": np.asarray(batch["x"])[:1],
        "R1": np.asarray(batch["R1"])[:1],
        "t1": np.asarray(batch["t1"])[:1],
        "K": np.asarray(batch["K"])[:1],
    }
    radius = float(np.linalg.norm(first_view["t1"][0]))
    orbit = orbit_poses(frames, radius=radius or 1.0, elevation=0.3)
    target_poses = {
        "R2": np.asarray(orbit[None, :, :3, :3]),
        "t2": np.asarray(orbit[None, :, :3, 3]),
    }
    pool = max(2, k_max or (frames + 1))
    sampler = make_stochastic_sampler(model, schedule, diffusion,
                                      max_pool=pool)
    key = jax.random.PRNGKey(seed)

    def stage(params):
        staged = precision_lib.stage_params(params, precision)
        if precision == "int8":
            staged = precision_lib.make_resolver("int8")(staged)
        return staged

    def probe(params) -> float:
        imgs = autoregressive_generate(
            model, schedule, diffusion, stage(params), key, first_view,
            target_poses, max_pool=pool, sampler=sampler)
        imgs = np.asarray(jax.device_get(imgs))[0]  # (N, H, W, 3)
        return float(np.mean(np.asarray(adjacent_psnr(imgs))))

    return probe


def run_gate(store: RegistryStore, candidate_vid: str, *, channel: str,
             probe_fn: Callable, margin_db: float,
             event_cb: Optional[EventCb] = None,
             metric: str = "psnr") -> GateResult:
    """Score candidate vs the channel's incumbent; never moves pointers.

    The candidate payload is hash-verified on load, so a tampered or torn
    version fails here (IntegrityError) before any PSNR is computed.
    `metric` names the probe in the audit event (the trajectory-
    consistency gate runs through here too, with its own probe_fn)."""
    incumbent_vid = store.read_channel(channel)
    cand_manifest = store.verify(candidate_vid)
    candidate_params = store.load_params(candidate_vid, verify=False)
    candidate_psnr = probe_fn(candidate_params)
    incumbent_psnr = None
    if incumbent_vid and incumbent_vid != candidate_vid:
        incumbent_psnr = probe_fn(store.load_params(incumbent_vid))
    elif incumbent_vid == candidate_vid:
        incumbent_vid = None  # re-promoting the incumbent: bootstrap rule
    passed, reason = decide(candidate_psnr, incumbent_psnr, margin_db)
    result = GateResult(
        passed=passed, candidate=candidate_vid, incumbent=incumbent_vid,
        candidate_psnr=candidate_psnr, incumbent_psnr=incumbent_psnr,
        margin_db=margin_db, reason=reason)
    if event_cb is not None:
        inc = (f" vs incumbent {incumbent_vid} "
               f"{incumbent_psnr:.2f} dB" if incumbent_psnr is not None
               else "")
        event_cb(cand_manifest.step,
                 "gate_pass" if passed else "gate_fail",
                 f"channel {channel} [{metric}]: candidate "
                 f"{candidate_psnr:.2f} dB{inc}; {reason}", candidate_vid)
    return result


@dataclasses.dataclass(frozen=True)
class GateMatrixResult:
    """Per-(corpus × resolution) gate verdict (the ladder/mixer gate).

    `cells` rows: corpus, resolution, metric, candidate_psnr,
    incumbent_psnr, delta_db, passed, reason. The matrix passes only
    when EVERY cell passes — one regressed corpus or rung resolution
    blocks the promotion, margin-checked with the same decide() rule as
    the scalar gate."""

    passed: bool
    candidate: str
    incumbent: Optional[str]
    margin_db: float
    cells: tuple

    @property
    def worst(self) -> Optional[dict]:
        deltas = [c for c in self.cells if c["delta_db"] is not None]
        if not deltas:
            return None
        return min(deltas, key=lambda c: c["delta_db"])


def run_gate_matrix(store: RegistryStore, candidate_vid: str, *,
                    channel: str, cells, margin_db: float,
                    event_cb: Optional[EventCb] = None
                    ) -> GateMatrixResult:
    """Score candidate vs incumbent on EVERY (corpus, resolution) cell.

    `cells` is a sequence of dicts {corpus, resolution, metric,
    probe_fn} — cli._run_gates builds one per corpus of the mix × rung
    resolution of the ladder (registry item 5's eval matrix). Both
    versions are loaded ONCE and every probe scores the same trees, so
    an R×C matrix costs R·C probe runs, not R·C payload loads. Never
    moves pointers; emits one gate_pass/gate_fail audit event naming
    the worst cell."""
    incumbent_vid = store.read_channel(channel)
    cand_manifest = store.verify(candidate_vid)
    candidate_params = store.load_params(candidate_vid, verify=False)
    incumbent_params = None
    if incumbent_vid == candidate_vid:
        incumbent_vid = None  # re-promoting the incumbent: bootstrap rule
    elif incumbent_vid:
        incumbent_params = store.load_params(incumbent_vid)
    rows = []
    for cell in cells:
        cand = cell["probe_fn"](candidate_params)
        inc = (cell["probe_fn"](incumbent_params)
               if incumbent_params is not None else None)
        passed, reason = decide(cand, inc, margin_db)
        rows.append({
            "corpus": cell["corpus"],
            "resolution": int(cell["resolution"]),
            "metric": cell.get("metric", "psnr"),
            "candidate_psnr": cand,
            "incumbent_psnr": inc,
            "delta_db": None if inc is None else cand - inc,
            "passed": passed,
            "reason": reason,
        })
    result = GateMatrixResult(
        passed=all(r["passed"] for r in rows),
        candidate=candidate_vid, incumbent=incumbent_vid,
        margin_db=margin_db, cells=tuple(rows))
    if event_cb is not None:
        failed = [r for r in rows if not r["passed"]]
        worst = (min(failed, key=lambda r: r["delta_db"] or 0.0)
                 if failed else result.worst)
        detail = (f"channel {channel} matrix: {len(rows)} cells, "
                  f"{len(rows) - len(failed)} passed")
        if worst is not None:
            detail += (f"; worst {worst['corpus']}@{worst['resolution']}px"
                       f" [{worst['metric']}] {worst['candidate_psnr']:.2f}"
                       " dB" + (f" ({worst['delta_db']:+.2f} dB)"
                                if worst["delta_db"] is not None else ""))
        event_cb(cand_manifest.step,
                 "gate_pass" if result.passed else "gate_fail",
                 detail, candidate_vid)
    return result


def promote(store: RegistryStore, vid: str, *, channel: str = "stable",
            gate: Optional[GateResult] = None,
            event_cb: Optional[EventCb] = None) -> None:
    """Advance `channel` to `vid`. With a GateResult attached, a failed
    gate refuses the move (RegistryError) — auto-reject, pointer intact."""
    if gate is not None and not gate.passed:
        raise RegistryError(
            f"refusing to promote {vid} to {channel!r}: {gate.reason}")
    step = store.manifest(vid).step
    store.set_channel(channel, vid)
    if event_cb is not None:
        event_cb(step, "promote", f"channel {channel} -> {vid}", vid)


def rollback(store: RegistryStore, *, channel: str = "stable",
             event_cb: Optional[EventCb] = None) -> str:
    """Move `channel` back to its previous distinct version (the serving
    watcher picks the old weights up on its next poll)."""
    restored = store.rollback(channel)
    if event_cb is not None:
        event_cb(store.manifest(restored).step, "rollback",
                 f"channel {channel} rolled back to {restored}", restored)
    return restored
