"""Reload watcher: channel pointer → zero-downtime service swap.

A serving process subscribes to one registry channel (`stable` in
production). This thread polls the pointer every `registry.poll_s`
seconds; when it moves, the new version is hash-VERIFIED, loaded to host,
and handed to `SamplingService.swap_params`, which stages the tree AT THE
SERVING PRECISION (sample/precision.py: the published f32 payload is cast
to bf16 or weight-only-int8-quantized on host before upload, per
`serve.precision`) on the mesh alongside the live one and flips between
dispatches — requests in flight finish on the version they started on,
warm sampler programs survive (the program cache is keyed on
shapes/precision, not params), and the old tree is freed after the flip.

Failure policy — a circuit breaker, not a permanent blacklist. A version
that fails verification or staging is logged (`swap_fail` event) and the
breaker OPENS: the poller stops retrying that version, the service keeps
serving the old weights, and `nvs3d_swap_failures_total` ticks. After a
backoff that doubles with each consecutive failure (capped at
`breaker_cap_s`) the breaker goes HALF-OPEN and probes the same version
once — transient faults (torn copy mid-publish, flaky blob store) heal
without operator action, while a genuinely corrupt artifact re-opens the
breaker with a longer backoff instead of retry-storming. A pointer move
to a DIFFERENT version resets the breaker immediately: rolling the
channel back or forward is always safe and takes effect on the next poll.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from novel_view_synthesis_3d_tpu import obs
from novel_view_synthesis_3d_tpu.registry.gate import EventCb
from novel_view_synthesis_3d_tpu.registry.store import (
    RegistryError,
    RegistryStore,
)
from novel_view_synthesis_3d_tpu.utils import faultinject


class RegistryWatcher:
    def __init__(self, service, store: RegistryStore, channel: str, *,
                 poll_s: float = 2.0, event_cb: Optional[EventCb] = None,
                 breaker_base_s: Optional[float] = None,
                 breaker_cap_s: float = 300.0,
                 start: bool = True):
        self.service = service
        self.store = store
        self.channel = channel
        self.poll_s = max(0.01, float(poll_s))
        self.event_cb = event_cb
        self.swaps = 0
        self.failures = 0
        self.consecutive_failures = 0
        # Half-open probe cadence: default one poll period, so a flaky
        # artifact is re-tried on the next poll but never sooner.
        self.breaker_base_s = (float(breaker_base_s)
                               if breaker_base_s is not None
                               else self.poll_s)
        self.breaker_cap_s = float(breaker_cap_s)
        self._failed_vid: Optional[str] = None
        self._retry_at = 0.0  # monotonic deadline for the half-open probe
        self._swap_failures_total = obs.get_registry().counter(
            "nvs3d_swap_failures_total",
            "model swaps that failed verify/stage (breaker openings)")
        self._stop = threading.Event()
        self._poked = threading.Event()  # test hook: poll NOW
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="registry-watcher")
        if start:
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            self._poked.wait(timeout=self.poll_s)
            self._poked.clear()

    def poke(self) -> None:
        """Skip the remaining poll sleep (tests, admin endpoints)."""
        self._poked.set()

    def poll_once(self) -> Optional[str]:
        """One poll: swap if the channel moved; returns the version
        swapped to, else None."""
        try:
            vid = self.store.read_channel(self.channel)
        except OSError:
            return None
        if not vid or vid == self.service.model_version:
            return None
        half_open = False
        if vid == self._failed_vid:
            if time.monotonic() < self._retry_at:
                return None  # breaker open: don't retry-storm
            half_open = True  # backoff elapsed: single probe
        try:
            faultinject.maybe_serve_swap_fail()
            manifest = self.store.verify(vid)
            params = self.store.load_params(vid, verify=False)
            self.service.swap_params(params, vid, step=manifest.step,
                                     timeout=600.0)
        except Exception as exc:  # IntegrityError, torn IO, staging error
            self.failures += 1
            self._swap_failures_total.inc()
            if vid == self._failed_vid:
                self.consecutive_failures += 1
            else:
                self.consecutive_failures = 1
            self._failed_vid = vid
            backoff = min(self.breaker_cap_s,
                          self.breaker_base_s
                          * 2 ** (self.consecutive_failures - 1))
            self._retry_at = time.monotonic() + backoff
            if self.event_cb is not None:
                self.event_cb(0, "swap_fail",
                              f"channel {self.channel} -> {vid}: {exc!r}; "
                              "still serving "
                              f"{self.service.model_version or '<initial>'}"
                              f"; breaker open (failure "
                              f"{self.consecutive_failures}, "
                              f"{'half-open probe failed, ' if half_open else ''}"
                              f"retry in {backoff:.3g}s)",
                              vid)
            return None
        self.swaps += 1
        if half_open and self.event_cb is not None:
            self.event_cb(0, "swap_recover",
                          f"channel {self.channel} -> {vid}: half-open "
                          f"probe succeeded after "
                          f"{self.consecutive_failures} failure(s); "
                          "breaker closed", vid)
        self._failed_vid = None
        self.consecutive_failures = 0
        self._retry_at = 0.0
        return vid

    def stop(self) -> None:
        self._stop.set()
        self._poked.set()
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)
