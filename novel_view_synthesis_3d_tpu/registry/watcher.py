"""Reload watcher: channel pointer → zero-downtime service swap.

A serving process subscribes to one registry channel (`stable` in
production). This thread polls the pointer every `registry.poll_s`
seconds; when it moves, the new version is hash-VERIFIED, loaded to host,
and handed to `SamplingService.swap_params`, which stages the tree AT THE
SERVING PRECISION (sample/precision.py: the published f32 payload is cast
to bf16 or weight-only-int8-quantized on host before upload, per
`serve.precision`) on the mesh alongside the live one and flips between
dispatches — requests in flight finish on the version they started on,
warm sampler programs survive (the program cache is keyed on
shapes/precision, not params), and the old tree is freed after the flip.

Failure policy: a version that fails verification or staging is logged
(`swap_fail` event) and BLACKLISTED until the pointer moves again — the
service keeps serving the old weights, and the poller doesn't retry-storm
a known-bad artifact. Rolling the channel back is therefore always safe:
the watcher treats the restored pointer like any other move.
"""

from __future__ import annotations

import threading
from typing import Optional

from novel_view_synthesis_3d_tpu.registry.gate import EventCb
from novel_view_synthesis_3d_tpu.registry.store import (
    RegistryError,
    RegistryStore,
)


class RegistryWatcher:
    def __init__(self, service, store: RegistryStore, channel: str, *,
                 poll_s: float = 2.0, event_cb: Optional[EventCb] = None,
                 start: bool = True):
        self.service = service
        self.store = store
        self.channel = channel
        self.poll_s = max(0.01, float(poll_s))
        self.event_cb = event_cb
        self.swaps = 0
        self.failures = 0
        self._failed_vid: Optional[str] = None
        self._stop = threading.Event()
        self._poked = threading.Event()  # test hook: poll NOW
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="registry-watcher")
        if start:
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            self._poked.wait(timeout=self.poll_s)
            self._poked.clear()

    def poke(self) -> None:
        """Skip the remaining poll sleep (tests, admin endpoints)."""
        self._poked.set()

    def poll_once(self) -> Optional[str]:
        """One poll: swap if the channel moved; returns the version
        swapped to, else None."""
        try:
            vid = self.store.read_channel(self.channel)
        except OSError:
            return None
        if (not vid or vid == self.service.model_version
                or vid == self._failed_vid):
            return None
        try:
            manifest = self.store.verify(vid)
            params = self.store.load_params(vid, verify=False)
            self.service.swap_params(params, vid, step=manifest.step,
                                     timeout=600.0)
        except Exception as exc:  # IntegrityError, torn IO, staging error
            self.failures += 1
            self._failed_vid = vid  # no retry-storm on a bad artifact
            if self.event_cb is not None:
                self.event_cb(0, "swap_fail",
                              f"channel {self.channel} -> {vid}: {exc!r}; "
                              "still serving "
                              f"{self.service.model_version or '<initial>'}",
                              vid)
            return None
        self.swaps += 1
        self._failed_vid = None
        return vid

    def stop(self) -> None:
        self._stop.set()
        self._poked.set()
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)
