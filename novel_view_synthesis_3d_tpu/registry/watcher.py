"""Reload watcher: channel pointer → zero-downtime service swap.

A serving process subscribes to one registry channel (`stable` in
production). This thread polls the pointer every `registry.poll_s`
seconds; when it moves, the new version is hash-VERIFIED, loaded to host,
and handed to `SamplingService.swap_params`, which stages the tree AT THE
SERVING PRECISION (sample/precision.py: the published f32 payload is cast
to bf16 or weight-only-int8-quantized on host before upload, per
`serve.precision`) on the mesh alongside the live one and flips between
dispatches — requests in flight finish on the version they started on,
warm sampler programs survive (the program cache is keyed on
shapes/precision, not params), and the old tree is freed after the flip.

Failure policy — a circuit breaker, not a permanent blacklist. A version
that fails verification or staging is logged (`swap_fail` event) and the
breaker OPENS: the poller stops retrying that version, the service keeps
serving the old weights, and `nvs3d_swap_failures_total` ticks. After a
backoff that doubles with each consecutive failure (capped at
`breaker_cap_s`) the breaker goes HALF-OPEN and probes the same version
once — transient faults (torn copy mid-publish, flaky blob store) heal
without operator action, while a genuinely corrupt artifact re-opens the
breaker with a longer backoff instead of retry-storming. A pointer move
to a DIFFERENT version resets the breaker immediately: rolling the
channel back or forward is always safe and takes effect on the next poll.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from novel_view_synthesis_3d_tpu import obs
from novel_view_synthesis_3d_tpu.registry.gate import EventCb
from novel_view_synthesis_3d_tpu.registry.store import (
    RegistryError,
    RegistryStore,
)
from novel_view_synthesis_3d_tpu.utils import faultinject


# Gauge encoding for nvs3d_swap_breaker_state (docs/DESIGN.md "Fleet
# serving"): the deploy gate refuses a replica scraping as != 0.
_BREAKER_STATES = {"closed": 0.0, "open": 1.0, "half-open": 2.0}


class RegistryWatcher:
    def __init__(self, service, store: RegistryStore, channel: str, *,
                 poll_s: float = 2.0, event_cb: Optional[EventCb] = None,
                 breaker_base_s: Optional[float] = None,
                 breaker_cap_s: float = 300.0,
                 start: bool = True):
        self.service = service
        self.store = store
        self.channel = channel
        self.poll_s = max(0.01, float(poll_s))
        self.event_cb = event_cb
        self.swaps = 0
        self.failures = 0
        self.consecutive_failures = 0
        # Half-open probe cadence: default one poll period, so a flaky
        # artifact is re-tried on the next poll but never sooner.
        self.breaker_base_s = (float(breaker_base_s)
                               if breaker_base_s is not None
                               else self.poll_s)
        self.breaker_cap_s = float(breaker_cap_s)
        self._failed_vid: Optional[str] = None
        self._retry_at = 0.0  # monotonic deadline for the half-open probe
        self._swap_failures_total = obs.get_registry().counter(
            "nvs3d_swap_failures_total",
            "model swaps that failed verify/stage (breaker openings)")
        # Breaker state as a gauge so the fleet deploy gate
        # (serve/deploy.py) can refuse to proceed onto a replica whose
        # last swap failed, without tailing events.csv.
        self._breaker_gauge = obs.get_registry().gauge(
            "nvs3d_swap_breaker_state",
            "registry swap circuit breaker: 0 closed / 1 open / "
            "2 half-open")
        self._breaker_gauge.set(0.0)
        self._stop = threading.Event()
        self._poked = threading.Event()  # test hook: poll NOW
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="registry-watcher")
        if start:
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            self._poked.wait(timeout=self.poll_s)
            self._poked.clear()

    def poke(self) -> None:
        """Skip the remaining poll sleep (tests, admin endpoints)."""
        self._poked.set()

    @property
    def breaker_state(self) -> str:
        """'closed' | 'open' | 'half-open', derived live (open→half-open
        is a clock transition, not an event: the breaker goes half-open
        the moment the backoff deadline passes, whether or not a poll
        has probed yet). Reading refreshes the gauge so scrapes between
        polls see the clock transition too."""
        if self._failed_vid is None:
            state = "closed"
        elif time.monotonic() < self._retry_at:
            state = "open"
        else:
            state = "half-open"
        self._breaker_gauge.set(_BREAKER_STATES[state])
        return state

    def poll_once(self) -> Optional[str]:
        """One poll: swap if the channel moved; returns the version
        swapped to, else None."""
        try:
            vid = self.store.read_channel(self.channel)
        except OSError:
            return None
        if self._failed_vid is not None and vid \
                and vid != self._failed_vid:
            # The channel moved OFF the artifact that tripped the
            # breaker (a rollback, or a fresh publish superseding the
            # bad one). The breaker guards that artifact, not the
            # channel — reset so the new target gets a clean first
            # attempt instead of inheriting a cooldown it never earned.
            self._failed_vid = None
            self.consecutive_failures = 0
            self._retry_at = 0.0
            self._breaker_gauge.set(_BREAKER_STATES["closed"])
        if not vid or vid == self.service.model_version:
            return None
        half_open = False
        if vid == self._failed_vid:
            if time.monotonic() < self._retry_at:
                return None  # breaker open: don't retry-storm
            half_open = True  # backoff elapsed: single probe
        try:
            faultinject.maybe_serve_swap_fail()
            manifest = self.store.verify(vid)
            params = self.store.load_params(vid, verify=False)
            self.service.swap_params(params, vid, step=manifest.step,
                                     timeout=600.0)
        except Exception as exc:  # IntegrityError, torn IO, staging error
            self.failures += 1
            self._swap_failures_total.inc()
            if vid == self._failed_vid:
                self.consecutive_failures += 1
            else:
                self.consecutive_failures = 1
            self._failed_vid = vid
            backoff = min(self.breaker_cap_s,
                          self.breaker_base_s
                          * 2 ** (self.consecutive_failures - 1))
            self._retry_at = time.monotonic() + backoff
            self._breaker_gauge.set(_BREAKER_STATES["open"])
            if self.event_cb is not None:
                self.event_cb(0, "swap_fail",
                              f"channel {self.channel} -> {vid}: {exc!r}; "
                              "still serving "
                              f"{self.service.model_version or '<initial>'}"
                              f"; breaker open (failure "
                              f"{self.consecutive_failures}, "
                              f"{'half-open probe failed, ' if half_open else ''}"
                              f"retry in {backoff:.3g}s)",
                              vid)
            return None
        self.swaps += 1
        if half_open and self.event_cb is not None:
            self.event_cb(0, "swap_recover",
                          f"channel {self.channel} -> {vid}: half-open "
                          f"probe succeeded after "
                          f"{self.consecutive_failures} failure(s); "
                          "breaker closed", vid)
        self._failed_vid = None
        self.consecutive_failures = 0
        self._retry_at = 0.0
        self._breaker_gauge.set(_BREAKER_STATES["closed"])
        return vid

    def stop(self) -> None:
        self._stop.set()
        self._poked.set()
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)
