"""On-disk versioned model registry with atomic publish + channel pointers.

Layout (one registry root, any filesystem):

    <root>/versions/<version>/manifest.json   # integrity contract
    <root>/versions/<version>/params.msgpack  # the weights payload
    <root>/channels/<name>                    # pointer file: one version id
    <root>/channels/<name>.history            # append-only promote log
    <root>/.staging/ , <root>/.trash/         # never read by consumers

Concurrency contract — the part that makes zero-downtime reload safe:

  - PUBLISH is write-to-temp + per-file fsync + one atomic directory
    rename: a reader either sees no version or a complete one, never a
    torn one (same discipline as the checkpoint layer's torn-write
    defense, at the filesystem level instead of Orbax's).
  - CHANNEL moves are write-temp + `os.replace` of a one-line pointer
    file: a poller reads the old or the new version id, never a partial
    write.
  - GC renames a version into `.trash/` first (atomic disappearance),
    then deletes at leisure — a concurrent reader that already resolved
    the id may lose the race and must treat a missing version as "gone",
    not corrupt.

Verification re-hashes every payload file against the manifest — a
flipped byte (bad disk, truncated copy, manual tampering) is an
`IntegrityError`, not garbage weights on the mesh.
"""

from __future__ import annotations

import os
import shutil
import time
import uuid
from typing import Dict, List, Optional

from novel_view_synthesis_3d_tpu.registry.manifest import (
    MANIFEST_FILE,
    PARAMS_FILE,
    VersionManifest,
    digest_bytes,
    file_sha256,
    version_id,
)


class RegistryError(RuntimeError):
    """Base class for registry failures."""


class IntegrityError(RegistryError):
    """A version's payload does not match its manifest hashes."""


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds: rename is still atomic
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_file_synced(path: str, payload: bytes) -> None:
    with open(path, "wb") as fh:
        fh.write(payload)
        fh.flush()
        os.fsync(fh.fileno())


class RegistryStore:
    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.versions_dir = os.path.join(self.root, "versions")
        self.channels_dir = os.path.join(self.root, "channels")
        os.makedirs(self.versions_dir, exist_ok=True)
        os.makedirs(self.channels_dir, exist_ok=True)

    # -- publish -------------------------------------------------------
    def publish_bytes(self, payload: bytes, *, step: int, ema: bool,
                      fmt: str = "native", config_digest: str = "",
                      notes: str = "",
                      channel: Optional[str] = "latest",
                      extra_files: Optional[Dict[str, bytes]] = None
                      ) -> VersionManifest:
        """Publish one params payload as a new version; returns its
        manifest. Idempotent: identical (step, bytes) re-publishes resolve
        to the already-published version. `channel` (default `latest`)
        is pointed at the new version afterwards; None skips the pointer.
        """
        digest = digest_bytes(payload)
        vid = version_id(step, digest)
        final = os.path.join(self.versions_dir, vid)
        if os.path.isdir(final):
            existing = self.verify(vid)
            if channel:
                self.set_channel(channel, vid)
            return existing
        files = {PARAMS_FILE: {"sha256": digest, "bytes": len(payload)}}
        extra_files = extra_files or {}
        for name, blob in extra_files.items():
            files[name] = {"sha256": digest_bytes(blob), "bytes": len(blob)}
        manifest = VersionManifest(
            version=vid, step=int(step), ema=bool(ema), files=files,
            fmt=fmt, config_digest=config_digest, created=time.time(),
            notes=notes)
        staging_root = os.path.join(self.root, ".staging")
        os.makedirs(staging_root, exist_ok=True)
        tmp = os.path.join(staging_root, f"{vid}.{os.getpid()}."
                                         f"{uuid.uuid4().hex[:8]}")
        os.makedirs(tmp)
        try:
            _write_file_synced(os.path.join(tmp, PARAMS_FILE), payload)
            for name, blob in extra_files.items():
                _write_file_synced(os.path.join(tmp, name), blob)
            _write_file_synced(os.path.join(tmp, MANIFEST_FILE),
                               manifest.to_json().encode())
            _fsync_dir(tmp)
            try:
                os.rename(tmp, final)  # the atomic appearance
            except OSError:
                if os.path.isdir(final):
                    # Concurrent publisher of the same content won the
                    # rename; its version is byte-identical by content
                    # addressing — adopt it.
                    shutil.rmtree(tmp, ignore_errors=True)
                else:
                    raise
            _fsync_dir(self.versions_dir)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        if channel:
            self.set_channel(channel, vid)
        return manifest

    def publish_params(self, params_tree, **kw) -> VersionManifest:
        """Publish a flax param pytree (device or host leaves)."""
        import jax
        import numpy as np
        from flax import serialization

        payload = serialization.msgpack_serialize(
            jax.tree.map(np.asarray, params_tree))
        return self.publish_bytes(payload, **kw)

    # -- read ----------------------------------------------------------
    def list_versions(self) -> List[VersionManifest]:
        """Readable manifests, oldest step first. Versions that vanish
        mid-listing (a concurrent gc) or hold an unreadable manifest are
        skipped — listing must never crash on someone else's race."""
        out = []
        try:
            entries = sorted(os.listdir(self.versions_dir))
        except OSError:
            return []
        for vid in entries:
            try:
                out.append(self.manifest(vid))
            except (RegistryError, OSError, ValueError):
                continue
        out.sort(key=lambda m: (m.step, m.created, m.version))
        return out

    def manifest(self, vid: str) -> VersionManifest:
        path = os.path.join(self.versions_dir, vid, MANIFEST_FILE)
        try:
            with open(path) as fh:
                m = VersionManifest.from_json(fh.read())
        except FileNotFoundError:
            raise RegistryError(
                f"version {vid!r} not found under {self.versions_dir}")
        if m.version != vid:
            raise IntegrityError(
                f"manifest under {vid!r} names version {m.version!r} — "
                "directory was renamed or copied by hand")
        return m

    def verify(self, vid: str) -> VersionManifest:
        """Re-hash every payload file against the manifest; raises
        IntegrityError on any mismatch (tamper/torn-copy detection)."""
        m = self.manifest(vid)
        vdir = os.path.join(self.versions_dir, vid)
        for name, entry in m.files.items():
            path = os.path.join(vdir, name)
            if not os.path.exists(path):
                raise IntegrityError(
                    f"version {vid}: payload file {name!r} is missing")
            size = os.path.getsize(path)
            if size != int(entry.get("bytes", size)):
                raise IntegrityError(
                    f"version {vid}: {name} is {size} bytes, manifest "
                    f"says {entry['bytes']}")
            got = file_sha256(path)
            if got != entry["sha256"]:
                raise IntegrityError(
                    f"version {vid}: {name} sha256 {got[:12]}… does not "
                    f"match manifest {entry['sha256'][:12]}… — the "
                    "payload was modified after publish")
        return m

    def load_params(self, vid: str, verify: bool = True):
        """The version's params pytree (numpy leaves). `verify` (default)
        re-hashes first so tampered bytes never reach the mesh."""
        from flax import serialization

        m = self.verify(vid) if verify else self.manifest(vid)
        if m.fmt != "native":
            raise RegistryError(
                f"version {vid} holds a {m.fmt!r}-format payload — only "
                "'native' versions are servable (reference exports are "
                "for the reference codebase's restore path)")
        with open(os.path.join(self.versions_dir, vid, PARAMS_FILE),
                  "rb") as fh:
            return serialization.msgpack_restore(fh.read())

    # -- channels ------------------------------------------------------
    def read_channel(self, name: str) -> Optional[str]:
        try:
            with open(os.path.join(self.channels_dir, name)) as fh:
                vid = fh.read().strip()
        except FileNotFoundError:
            return None
        return vid or None

    def channels(self) -> Dict[str, str]:
        out = {}
        try:
            names = os.listdir(self.channels_dir)
        except OSError:
            return out
        for name in sorted(names):
            if name.startswith(".") or name.endswith(".history"):
                continue
            vid = self.read_channel(name)
            if vid:
                out[name] = vid
        return out

    def set_channel(self, name: str, vid: str, *,
                    require_exists: bool = True) -> None:
        if require_exists and not os.path.isdir(
                os.path.join(self.versions_dir, vid)):
            raise RegistryError(
                f"cannot point channel {name!r} at unknown version {vid!r}")
        tmp = os.path.join(self.channels_dir,
                           f".tmp.{name}.{uuid.uuid4().hex[:8]}")
        _write_file_synced(tmp, (vid + "\n").encode())
        os.replace(tmp, os.path.join(self.channels_dir, name))
        _fsync_dir(self.channels_dir)
        with open(os.path.join(self.channels_dir, name + ".history"),
                  "a") as fh:
            fh.write(f"{time.time():.3f} {vid}\n")
            fh.flush()
            os.fsync(fh.fileno())

    def channel_history(self, name: str) -> List[str]:
        """Version ids the channel has pointed at, oldest first."""
        try:
            with open(os.path.join(self.channels_dir,
                                   name + ".history")) as fh:
                lines = fh.read().splitlines()
        except FileNotFoundError:
            return []
        out = []
        for ln in lines:
            parts = ln.split()
            if len(parts) == 2:
                out.append(parts[1])
        return out

    def rollback(self, name: str) -> str:
        """Move the channel back to the version it pointed at before the
        current one; returns the restored version id."""
        current = self.read_channel(name)
        history = self.channel_history(name)
        for vid in reversed(history):
            if vid != current and os.path.isdir(
                    os.path.join(self.versions_dir, vid)):
                self.set_channel(name, vid)
                return vid
        raise RegistryError(
            f"channel {name!r} has no previous distinct version to roll "
            f"back to (current: {current!r})")

    # -- gc ------------------------------------------------------------
    def gc(self, keep: int) -> List[str]:
        """Delete all but the newest `keep` versions; versions any channel
        points at are always kept. Returns the deleted version ids."""
        if keep < 1:
            raise ValueError(f"gc keep={keep} must be >= 1")
        manifests = self.list_versions()
        pinned = set(self.channels().values())
        victims = [m.version for m in manifests[:-keep]
                   if m.version not in pinned]
        trash_root = os.path.join(self.root, ".trash")
        deleted = []
        for vid in victims:
            dst = os.path.join(trash_root,
                               f"{vid}.{uuid.uuid4().hex[:8]}")
            os.makedirs(trash_root, exist_ok=True)
            try:
                # Atomic disappearance first, slow rmtree second: readers
                # never observe a half-deleted version directory.
                os.rename(os.path.join(self.versions_dir, vid), dst)
            except OSError:
                continue  # concurrent gc won the race
            shutil.rmtree(dst, ignore_errors=True)
            deleted.append(vid)
        return deleted
