"""Version manifest: the integrity contract of one published model.

Every registry version directory holds exactly one `manifest.json`
describing the artifact next to it: the training step it came from,
whether it is the EMA tree, a digest of the weight-shaping config
sections (model + diffusion — the parts that decide whether a serving
process can load it), and a sha256 per payload file. The version id is
CONTENT-ADDRESSED — `<step>-<sha256 prefix of the params payload>` — so
re-publishing identical bytes lands on the same version (idempotent) and
two different trees can never collide under one id.

Pure stdlib + json: the supervisor-side tooling (`registry list/gc`) must
be able to inspect a registry without touching jax.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, Optional

MANIFEST_FILE = "manifest.json"
PARAMS_FILE = "params.msgpack"

# Payload layouts a manifest can describe: 'native' = this repo's flax
# param dict (what the service loads), 'reference' = the reference
# codebase's msgpack layout (`nvs3d export --registry`).
FORMATS = ("native", "reference")


def digest_bytes(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()


def file_sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        while True:
            block = fh.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def config_digest(cfg) -> str:
    """Digest of the weight-shaping config sections (model + diffusion).

    Two checkpoints are registry-compatible iff these sections match —
    train-loop knobs (lr, batch) and serving knobs deliberately don't
    participate, so a re-tuned run publishes into the same lineage."""
    d = cfg.to_dict()
    payload = json.dumps({"model": d.get("model", {}),
                          "diffusion": d.get("diffusion", {})},
                         sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()


def version_id(step: int, payload_digest: str) -> str:
    """`<step:08d>-<digest[:12]>`: lexical order == step order (ls-able),
    content hash makes the id collision-free across trees."""
    return f"{int(step):08d}-{payload_digest[:12]}"


@dataclasses.dataclass(frozen=True)
class VersionManifest:
    version: str
    step: int
    ema: bool
    # name -> {"sha256": hex, "bytes": int} for every payload file in the
    # version directory (manifest.json itself excluded).
    files: Dict[str, Dict[str, Any]]
    fmt: str = "native"
    config_digest: str = ""
    created: float = 0.0  # unix seconds at publish
    notes: str = ""

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2,
                          sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "VersionManifest":
        d = json.loads(s)
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - fields
        if unknown:
            raise ValueError(
                f"manifest holds unknown fields {sorted(unknown)} — "
                "written by a newer build? refusing to guess")
        return cls(**d)

    def payload_digest(self, name: str = PARAMS_FILE) -> Optional[str]:
        entry = self.files.get(name)
        return None if entry is None else entry.get("sha256")
