"""Trainer-side publisher: EMA snapshots → registry, off the step loop.

The trainer calls `publish_async(step, host_tree)` every
`registry.publish_every` steps with an already-host-resident numpy param
tree (the host-EMA buffer when the run keeps one — zero extra transfer).
Everything slow — integrity verification, msgpack serialization, sha256,
fsync'd write, atomic rename — happens on ONE worker thread; the step
loop's cost is handing over a reference.

Backpressure is coalescing, not blocking: if a publish is still writing
when the next cadence fires, the pending snapshot is REPLACED (newest
wins) and the superseded step is logged as `publish_skip`. A slow or
wedged filesystem can therefore delay publications but can never stall
training — the same degrade-don't-block policy the checkpoint save path
uses.

Integrity reuses the checkpoint layer's verification primitive
(`train/checkpoint.nonfinite_leaf_count`): a NaN-poisoned snapshot is
refused at the publisher (`publish_reject` event) instead of reaching the
`latest` channel where a canary would load it.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from novel_view_synthesis_3d_tpu.registry.gate import EventCb
from novel_view_synthesis_3d_tpu.registry.store import RegistryStore


class RegistryPublisher:
    def __init__(self, store: RegistryStore, *, ema: bool,
                 config_digest: str = "", channel: str = "latest",
                 event_cb: Optional[EventCb] = None):
        self.store = store
        self.ema = ema
        self.config_digest = config_digest
        self.channel = channel
        self.event_cb = event_cb
        self.published: List[str] = []  # version ids, publish order
        self.rejected = 0  # non-finite snapshots refused
        self.skipped = 0   # snapshots superseded before writing
        self.failures = 0  # store/filesystem errors (logged, non-fatal)
        self._pending: Optional[tuple] = None  # (step, tree)
        self._cv = threading.Condition()
        self._busy = False
        self._stop = False
        self._worker = threading.Thread(
            target=self._run, daemon=True, name="registry-publisher")
        self._worker.start()

    # -- trainer-facing ------------------------------------------------
    def publish_async(self, step: int, host_tree) -> None:
        """Hand one snapshot to the worker; returns immediately. A still-
        pending older snapshot is superseded (newest wins)."""
        with self._cv:
            if self._pending is not None:
                self.skipped += 1
                self._event(self._pending[0], "publish_skip",
                            f"superseded by step {step} before writing")
            self._pending = (int(step), host_tree)
            self._cv.notify_all()

    def publish(self, step: int, host_tree) -> Optional[str]:
        """Synchronous publish (CLI/tests); returns the version id or
        None when the snapshot was rejected."""
        return self._publish(int(step), host_tree)

    def drain(self, timeout: float = 60.0) -> bool:
        """Wait until no snapshot is pending or in flight."""
        with self._cv:
            return self._cv.wait_for(
                lambda: self._pending is None and not self._busy,
                timeout=timeout)

    def stop(self, drain: bool = True, timeout: float = 60.0) -> None:
        if drain:
            self.drain(timeout)
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._worker.join(timeout=10.0)

    # -- worker --------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cv:
                self._cv.wait_for(
                    lambda: self._stop or self._pending is not None)
                if self._stop:
                    return
                step, tree = self._pending
                self._pending = None
                self._busy = True
            try:
                self._publish(step, tree)
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def _publish(self, step: int, tree) -> Optional[str]:
        from novel_view_synthesis_3d_tpu.registry.store import RegistryError
        from novel_view_synthesis_3d_tpu.train.checkpoint import (
            nonfinite_leaf_count)

        bad = nonfinite_leaf_count(tree)
        if bad:
            self.rejected += 1
            self._event(step, "publish_reject",
                        f"snapshot holds {bad} non-finite leaves — not "
                        "published")
            return None
        try:
            m = self.store.publish_params(
                tree, step=step, ema=self.ema,
                config_digest=self.config_digest, channel=self.channel)
        except (RegistryError, OSError) as exc:
            # Degrade loudly: the registry is a convenience lane next to
            # the checkpoint (the durable record); a full disk here must
            # not kill a multi-day run.
            self.failures += 1
            self._event(step, "publish_fail", f"{exc!r}")
            return None
        self.published.append(m.version)
        self._event(step, "model_publish",
                    f"channel {self.channel} <- {m.version} "
                    f"(ema={m.ema})", m.version)
        return m.version

    def _event(self, step: int, kind: str, detail: str,
               version: str = "") -> None:
        if self.event_cb is not None:
            try:
                self.event_cb(step, kind, detail, version)
            except OSError:
                pass  # event logging must never be the publishing fault
