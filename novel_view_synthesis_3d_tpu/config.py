"""Single config tree for the whole framework.

The reference has no config system at all: hyperparameters are dataclass
defaults (`/root/reference/model/xunet.py:207-215`), Trainer keyword defaults
(`/root/reference/train.py:82-88`), or module constants
(`/root/reference/sampling.py:55,66,134`), and two key model attributes
(`ch_mult`, `attn_resolutions`) are frozen class attributes that cannot be
overridden without editing the source. Here every knob from SURVEY.md §2.2/§5.6
is a real, serializable field, with the BASELINE.json config ladder as presets.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """X-UNet hyperparameters (reference: model/xunet.py:205-215)."""

    ch: int = 32
    ch_mult: Tuple[int, ...] = (1, 2)
    emb_ch: int = 32
    num_res_blocks: int = 2
    # DDPM-style superset filter: attention runs at every UNet level whose
    # resolution is in this set; entries with no matching level are inert
    # by design (one list serves all depths/image sizes). validate()
    # rejects lists where NO level matches, and entries that could never
    # match at any depth (not power-of-two-related to the sidelength).
    attn_resolutions: Tuple[int, ...] = (8, 16, 32)
    attn_heads: int = 4
    dropout: float = 0.1
    use_pos_emb: bool = False
    use_ref_pose_emb: bool = False
    # Number of conditioning frames (k in 3DiM). The reference hardcodes 1
    # (frame axis F = k+1 = 2 throughout model/xunet.py); here it is a field.
    num_cond_frames: int = 1
    # --- behavior-vs-bug compat flags (SURVEY.md §7 ledger) ---
    # Reference GroupNorm shares statistics across both frames
    # (model/xunet.py:46-52); per-frame stats are what the architecture
    # intends. Default True = per-frame; False reproduces reference behavior.
    groupnorm_per_frame: bool = True
    # Reference attention has no output projection (commented out at
    # model/xunet.py:126). Default False matches the reference.
    attn_out_proj: bool = False
    # --- TPU knobs ---
    dtype: str = "float32"  # compute dtype: "float32" | "bfloat16"
    param_dtype: str = "float32"
    # Rematerialization of UNet blocks: False/'none' = off; True/'full' =
    # jax.checkpoint each block (min memory, max recompute); 'dots' = save
    # conv/matmul outputs, recompute elementwise chains
    # (checkpoint_policies.dots_saveable) — cuts HBM traffic without
    # re-running convs, often the right setting for bandwidth-bound configs.
    remat: Any = False
    # Fused Pallas attention kernel (ops/flash_attention.py) instead of the
    # XLA dot_product_attention path. "auto" (default) enables it on TPU
    # backends only and keeps the XLA path elsewhere; True forces the
    # kernel (interpret mode off-TPU, slow but exact); False forces the
    # XLA path. Measured +26-35% train step on v5e at tiny64 in ROUND 2,
    # BEFORE the r3 backward-path split (_PALLAS_BWD_MIN_HEAD_DIM) — the
    # r4 bench matrix re-validates with tiny64/base128 flash-off A/Bs
    # (results/tpu_r04/).
    use_flash_attention: Any = "auto"
    # Fused single-HBM-pass GroupNorm(+swish) Pallas kernel
    # (ops/fused_groupnorm.py) for the per-frame GN chains. False (default)
    # keeps the XLA norm until the kernel has a measured TPU win; "auto"
    # enables it on TPU backends; True forces it (interpret mode off-TPU).
    # Shared-stats GN (groupnorm_per_frame=False) and over-VMEM slabs fall
    # back to XLA automatically.
    use_fused_groupnorm: Any = False
    # Fused single-kernel SERVING attention (ops/serving_attention.py):
    # a forward-only Pallas kernel that keeps one (batch·head) attention
    # head entirely in VMEM — scores, softmax, and the value contraction
    # in one pass, no backward residuals. Sized for serving token counts
    # (H·W at the attn resolutions); shapes whose slabs exceed the VMEM
    # budget fall back to the XLA path per shape, and every decision is
    # recorded in a coverage registry that tools/summarize_bench.py
    # renders. "auto" enables it on TPU backends only; True forces the
    # kernel (interpret mode off-TPU — exact, slow, the tier-1 parity
    # path); False keeps XLA. Takes precedence over use_flash_attention
    # when both resolve on (flash keeps the trained backward path; this
    # kernel is inference-only).
    use_serving_attention: Any = False
    # Fused GroupNorm → FiLM-modulate → SiLU block epilogue
    # (ops/fused_epilogue.py): the ResnetBlock tail after the FiLM Dense
    # — normalize, scale/shift by the per-pixel FiLM tensors, activate —
    # runs as ONE Pallas pass per (B·F) row instead of three HBM
    # round-trips. The FiLM Dense projection itself stays in XLA (it is
    # a matmul; the kernel fuses the bandwidth-bound elementwise tail).
    # Same flag semantics as use_fused_groupnorm; requires
    # groupnorm_per_frame=True and falls back to XLA for over-VMEM slabs.
    use_fused_epilogue: Any = False
    # Sequence parallelism: shard the H·W token axis of every attention over
    # the mesh 'seq' axis and run ring attention (parallel/ring_attention.py,
    # ppermute over ICI). Requires mesh.seq > 1 and token counts divisible
    # by it; a no-op when the mesh has seq=1.
    sequence_parallel: bool = False
    # Scene-category conditioning (ROADMAP item 5): > 0 adds a ZERO-INIT
    # category embedding table (num_classes, emb_ch) inside
    # ConditioningProcessor_0, looked up by the batch's int32 `category`
    # ids and added to the logsnr embedding BEHIND the CFG cond-drop mask
    # (so classifier-free guidance and distillation drop it together with
    # the pose conditioning). Zero-init makes enabling it a numeric no-op
    # at init, and lets checkpoints taken at num_classes=0 load into a
    # num_classes>0 model via the versioned param-tree splice
    # (train/ladder.restore_with_growth). 0 = off (no table, param tree
    # unchanged).
    num_classes: int = 0

    @property
    def num_frames(self) -> int:
        return self.num_cond_frames + 1


@dataclasses.dataclass(frozen=True)
class DiffusionConfig:
    """Diffusion process (reference: sampling.py:16-53,73-76, T=1000 cosine)."""

    timesteps: int = 1000
    # 'cosine' (the reference's only schedule), 'linear' (Ho et al. 2020
    # 1e-4→0.02 ladder, endpoints scaled by 1000/T), or 'shifted_cosine'
    # (Hoogeboom et al. 2023 "simple diffusion": cosine logsnr shifted by
    # `logsnr_shift` — at resolution S set it to 2·log(64/S), e.g. −2.77 at
    # 256px, so high-res training sees as much signal destruction as 64px).
    # Non-cosine schedules condition the model on the exact per-timestep
    # log(ᾱ/(1−ᾱ)).
    schedule: str = "cosine"
    logsnr_shift: float = 0.0  # shifted_cosine only
    cosine_s: float = 0.008
    logsnr_min: float = -20.0
    logsnr_max: float = 20.0
    # What the network predicts / is trained against: 'eps' (the reference's
    # noise prediction), 'x0' (clean image), or 'v' (√ᾱε − √(1−ᾱ)x₀,
    # Salimans & Ho 2022). Train step and samplers both honor this.
    objective: str = "eps"
    # Sampling
    sample_timesteps: int = 1000  # respaced steps for the ancestral sampler
    guidance_weight: float = 3.0  # CFG w (reference sampling.py:134)
    # CFG rescale φ (Lin et al. 2023, arXiv 2305.08891 §3.4): after guidance,
    # rescale x̂₀ so its per-sample std matches the conditional prediction's,
    # then blend x̂₀ ← φ·rescaled + (1−φ)·guided. 0 = off (reference
    # behavior); ~0.7 counters the over-saturation large w causes.
    cfg_rescale: float = 0.0
    clip_denoised: bool = True
    # 'ddpm' = ancestral (the reference's sampler); 'ddim' = Song et al.
    # 2021 non-Markovian update — deterministic at ddim_eta=0, ancestral-like
    # at ddim_eta=1; pairs well with aggressive respacing (sample_timesteps).
    # 'dpm++' = DPM-Solver++(2M) (Lu et al. 2022) — deterministic
    # second-order multistep solver; comparable quality at ~8× fewer steps
    # (sample_timesteps 25–50 instead of 256+).
    sampler: str = "ddpm"
    ddim_eta: float = 0.0
    # Fused Pallas denoise-step kernel (ops/fused_step.py): everything
    # after the UNet forward — CFG combine, x̂₀ reconstruction + clip,
    # the ddpm/ddim update, the noise add — runs as ONE kernel call per
    # step instead of ~a dozen elementwise HLOs, consuming the per-row
    # (B, K) schedule-coefficient matrix as device arguments. Honored by
    # the serving samplers (sample/ddpm.make_request_sampler and
    # make_slot_step_fn — both serve.scheduler values share it). "auto"
    # enables it on TPU backends only; True forces it (interpret mode
    # off-TPU: exact, slow — the tier-1 parity path); False keeps the
    # unfused chain. dpm++ 2M cannot fuse (multistep history): True
    # errors, 'auto' falls back to the unfused scan ('request'
    # scheduler) / the first-order fallback fuses fine ('step').
    fused_step: Any = False
    # Stochastic multi-view conditioning for trajectory serving
    # (3DiM §3.2; docs/DESIGN.md "Trajectory serving & stochastic
    # conditioning"). True (default): each denoise step of a trajectory
    # row draws its conditioning view UNIFORMLY from the row's frame
    # bank with the slot's PRNG carry — the paper's protocol, what makes
    # a k=1 model render consistent orbits. False: condition every step
    # on the MOST RECENT bank frame (deterministic; an ablation/debug
    # mode, not the paper protocol). Changes the compiled step program
    # body, so it rides the stepper program-cache key; the bank gather
    # happens BEFORE the UNet forward either way, so diffusion.fused_step
    # kernels (ops/fused_step.py) fuse unchanged.
    stochastic_cond: Any = True


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """SRN-format dataset options (reference: dataset/data_loader.py:116-140)."""

    root_dir: str = "cars_train_val"
    img_sidelength: int = 64
    max_num_instances: int = -1
    max_observations_per_instance: int = 50
    specific_observation_idcs: Optional[Tuple[int, ...]] = None
    samples_per_instance: int = 1
    # Record backend: 'files' = walk the SRN per-scene PNG/pose tree (the
    # reference layout); 'packed' = read the sharded record format
    # (data/records.py — root_dir is then a `nvs3d pack` output dir with
    # index.json). Packed reads are per-host at shard granularity, served
    # through the compute-overlapped PipelinedLoader (decode worker pool
    # sized by num_workers, depth by prefetch), and produce bit-identical
    # training batches to 'files' for the same (seed, epoch, index). The
    # `loader` knob below only applies to 'files'.
    backend: str = "files"
    # Pipeline loader for backend='files': 'native' = C++ threaded loader
    # (native/libnvs3d_io.so, falls back to grain if the library can't
    # build), 'grain' = Grain worker processes, 'python' = in-process
    # iterator.
    loader: str = "native"
    num_workers: int = 8
    prefetch: int = 4
    shuffle_seed: int = 0
    # Data fault tolerance: a record whose image/pose fails to load is
    # QUARANTINED (skipped for the rest of the run, reported on stderr) and
    # a substitute record is drawn, up to this many consecutive redraws
    # before the batch is declared unbuildable. Uniform across the python,
    # Grain, and native backends. 0 = faults are fatal (old behavior).
    max_record_retries: int = 3
    # Corpus mixer (data/corpus.py; ROADMAP item 5): '' = off (root_dir is
    # the single corpus, exactly the pre-mixer behavior). Otherwise a
    # comma-separated list of `name:weight:path` entries, e.g.
    # "cars:3:/data/cars_packed,chairs:1:/data/chairs_packed" — N named
    # packed corpora sampled per batch-slot with probability weight/Σ,
    # drawn from the SAME single sequential rng as the plain packed
    # loader (a one-corpus mix is bit-identical to backend='packed'
    # today). Requires backend='packed'; every corpus must be a `nvs3d
    # pack` output dir. Batches gain int32 `corpus_id` (loss attribution)
    # and `category` (scene-category conditioning when model.num_classes
    # > 0) fields.
    mix: str = ""


@dataclasses.dataclass(frozen=True)
class WatchdogConfig:
    """Hang/stall watchdog (utils/watchdog.py; docs/DESIGN.md "Stall
    recovery"). Budgets are wall-clock seconds a single armed phase may
    run before the watchdog declares a stall, dumps a diagnosis bundle
    (all-thread stacks, heartbeat ages, device memory if reachable), logs
    a `stall` row in events.csv, and escalates. Compile budgets are
    separate from steady-state step budgets: the first dispatch of a jitted
    program legitimately takes minutes (remote-tunnel XLA compiles have
    been observed at 30+ min at base128), while a steady-state step that
    takes 10 minutes is a wedged backend. Defaults are generous on purpose
    — the watchdog exists to catch the hour-scale silent hangs that have
    eaten whole bench rounds (BENCH_r0* rc=3, the 2400 s base128 sampling
    stall), not to police slow steps."""

    enabled: bool = True
    # Monitor thread poll interval. Stall detection latency is one
    # interval past the budget; the thread is asleep otherwise.
    check_interval_s: float = 2.0
    # Per-phase budgets (seconds). A phase is armed while the trainer is
    # inside it; 0 disables that phase's deadline.
    data_fetch_s: float = 600.0
    step_s: float = 600.0
    compile_s: float = 3600.0  # first dispatch of each jitted program
    checkpoint_save_s: float = 900.0
    eval_s: float = 1800.0
    # Hard-exit grace: if an armed phase is STILL stuck this many seconds
    # AFTER its budget expired (the main thread never came back to observe
    # the soft stall flag — a true wedge, e.g. uninterruptible tunnel IO),
    # the monitor thread dumps a final diagnosis and os._exit()s with
    # EXIT_STALL so a supervisor can restart the host. 0 = disabled.
    hard_exit_s: float = 0.0


@dataclasses.dataclass(frozen=True)
class NumericsConfig:
    """In-jit per-layer-group numerics observatory (obs/numerics.py;
    docs/DESIGN.md "Training numerics & compile observatory").

    The train step ALWAYS emits per-group grad norm, param norm,
    update/param RMS ratio, grad max-abs, and non-finite leaf counts as
    READ-ONLY (G,)-shaped reductions grouped by the pipeline op list
    (models/xunet.pipeline_op_specs); `enabled` gates only the HOST-side
    consumer (numerics.jsonl rows, `nvs3d_grad_norm{group=...}` gauges,
    the EWMA spike detector's `numerics_spike` events). That split is
    the contract: flipping `enabled` is bitwise identical with zero
    recompiles by construction — one step program either way, with
    host-side decimation per `every`."""

    # Host-side publication switch. The device-side reductions are a
    # fixed part of the step program (see the module docstring).
    enabled: bool = False
    # Host-side decimation: device_get + publish the per-group stats every
    # N steps. The device-side reductions run every step either way (same
    # XLA program regardless); this only bounds host traffic.
    every: int = 1
    # EWMA spike detector: flag a group whose grad norm sits more than
    # this many EWMA standard deviations above its running mean.
    spike_z: float = 6.0
    # Decay of the per-group EWMA mean/variance the z-score is computed
    # against (0.9 ≈ a ~10-sample window).
    ewma_decay: float = 0.9


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Training loop options (reference: train.py:82-126)."""

    batch_size: int = 2  # GLOBAL batch (sharded over the data axis)
    lr: float = 1e-4
    num_steps: int = 100_000
    save_every: int = 1000
    log_every: int = 50
    sample_every: int = 0  # 0 = never dump eval samples during training
    # Every N steps, sample the held batch's target poses and log PSNR/SSIM
    # vs ground truth to results_folder/eval.csv (0 = off). Cheap in-loop
    # quality signal; full held-out evaluation stays in the `eval` CLI.
    eval_every: int = 0
    eval_sample_steps: int = 64  # respaced steps for the in-loop eval
    # Held-out SRN tree for the in-loop probe: when set, the eval.csv curve
    # scores these views (true validation); when empty, the probe scores a
    # fixed batch of TRAINING views (reconstruction-progress signal only).
    eval_folder: str = ""
    seed: int = 0
    # Per-sample probability of dropping pose conditioning for CFG
    # (reference: train.py:64 uses 0.1, but bakes the mask at trace time).
    cond_drop_prob: float = 0.1
    # 'mse' (per-element mean squared error, the sane default) or 'frobenius'
    # (reference train.py:67: L2 norm of the whole flattened residual).
    loss: str = "mse"
    # Optimizer: 'adam' (reference train.py:46) or 'adafactor' (factored
    # second moments + no first moment — optimizer state drops from 2x
    # param bytes to ~sqrt-sized row/col stats; the fallback that gives
    # paper256 real HBM margin on a 16G chip, see train/state.make_optimizer)
    optimizer: str = "adam"
    grad_clip: float = 0.0  # 0 = off
    # Adam first-moment (m) storage dtype. 'bfloat16' halves m's HBM
    # footprint (0.5× param bytes saved) with negligible quality impact —
    # m is a fast EMA (β₁=0.9) whose per-step relative increments are well
    # above bf16 resolution. The second moment v stays f32 (its increments
    # are squared-gradient-scale and underflow bf16), and so does the
    # sampling EMA (decay 0.9999 increments sit below bf16 ulp — a bf16
    # EMA would freeze). Default f32 = exact reference-equivalent Adam.
    adam_mu_dtype: str = "float32"
    warmup_steps: int = 0
    # LR decay after warmup: 'constant' (reference behavior, train.py:46)
    # or 'cosine' (decay to lr_final_fraction·lr over num_steps).
    lr_schedule: str = "constant"
    lr_final_fraction: float = 0.1
    # Per-timestep loss weighting: 'none' (uniform — the reference and DDPM
    # default) or 'min_snr' (min-SNR-γ, Hang et al. 2023: clamp the
    # effective SNR-dependent weight at γ so easy low-noise timesteps stop
    # dominating training). Requires loss='mse' (the frobenius compat loss
    # is a whole-batch norm with no per-sample decomposition).
    loss_weighting: str = "none"
    min_snr_gamma: float = 5.0
    # Micro-batching inside the jitted step (lax.scan over batch slices,
    # gradients averaged) — trains configs whose full-batch activations
    # exceed HBM (paper256 ladder) without changing the effective batch.
    # This is an UPPER BOUND: the step uses the largest divisor of the
    # per-data-shard batch ≤ this value (train/step.effective_accum_steps),
    # so a single-chip tuning stays valid on any mesh. 1 = off.
    grad_accum_steps: int = 1
    # Fused multi-step dispatch: lax.scan over K staged batches in ONE XLA
    # program. Each scanned step is the full train step (fresh data, fresh
    # fold_in(rng, step) keys, optimizer update) — semantics identical to K
    # single dispatches; what changes is K-1 fewer host dispatch round
    # trips, which dominate wall clock for small models and remote-device
    # (tunneled) runtimes. Cadences (log/save/eval/sample_every, num_steps,
    # profile window) must be multiples of K — validate() enforces.
    steps_per_dispatch: int = 1
    # ZeRO/FSDP: shard params + optimizer state over the mesh 'data' axis
    # (parallel/mesh.fsdp_spec). The reference replicates everything per
    # device (train.py:46).
    fsdp: bool = False
    # Weight-update sharding ('replicated' or 'zero'). 'zero' keeps params
    # REPLICATED for fwd/bwd (unlike fsdp, no per-layer all-gathers in the
    # forward) but shards the Adam moments + EMA over the mesh 'data' axis
    # (parallel/zero.py): gradients reduce-scatter into 1/N shards, the
    # update runs on each replica's shard, and fresh params all-gather out —
    # "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
    # Training" (Xu et al. 2020). opt_state+EMA device bytes drop to
    # ~1/data_shards while the step stays numerically identical to
    # 'replicated'. Requires optimizer='adam' (adafactor's factored second
    # moments don't survive the flatten/pad shard layout) and fsdp=False
    # (fsdp already shards the whole state its own way).
    update_sharding: str = "replicated"
    # Tensor parallelism: shard attention heads + conv/dense output channels
    # over the mesh 'model' axis (parallel/mesh.tp_spec). No-op unless
    # mesh.model > 1. The reference has no TP (SURVEY.md §2.3).
    tp: bool = False
    ema_decay: float = 0.0  # 0 = off; 3DiM paper uses EMA for sampling
    # Host-side EMA: keep the EMA buffer in host RAM instead of HBM
    # (frees 4 bytes/param on-chip — 2.6G for the 708M-param paper256
    # model, the margin between fitting a 16G chip and OOM). The Trainer
    # pulls params every ema_host_every steps and folds them in with the
    # decay^k correction (ema ← d^k·ema + (1−d^k)·params — the standard
    # sparse-EMA update; exact for k=1). Checkpointed with the state.
    ema_host: bool = False
    ema_host_every: int = 25
    # Dtype for the in-loop probe's pinned param copy (sample/eval probes).
    # '' = keep the param/EMA dtype (f32 — exact). 'bfloat16' halves the
    # probe pin: at paper256 scale the f32 probe copy is ~2.6G on a chip
    # already at ~15.3G of 15.75G (results/tpu_r04/analyze_paper256.out) —
    # the probe would OOM mid-training. The probe is a trend signal
    # (eval.csv curve), and the paper256 model computes in bf16 anyway, so
    # bf16 probe weights cost ~nothing in signal. The probe copy is
    # explicitly freed after each probe either way.
    probe_dtype: str = ""
    results_folder: str = "./results"
    checkpoint_dir: str = "./checkpoints"
    resume: bool = True  # auto-resume from latest checkpoint (ref: absent)
    # --- observability (SURVEY.md §5.1-5.2: the reference has none) ---
    # jax.profiler trace window: [profile_from, profile_from+profile_steps).
    # Traces land in <results_folder>/profile; 0 steps disables.
    profile_from: int = 10
    profile_steps: int = 0
    # Debug mode: jax_debug_nans (NaN source localization in jitted code).
    debug_nans: bool = False
    # Checkpoint + clean exit on SIGTERM (TPU preemption); with resume=True
    # the rescheduled run continues from the last step.
    handle_preemption: bool = True
    # --- fault tolerance: the guard → rollback → fallback ladder ---
    # (docs/DESIGN.md "Fault tolerance"; SURVEY.md §5.3-§5.4 — the
    # reference dies on the first NaN and bricks on a torn checkpoint.)
    # Step anomaly guard (train/guard.py): skip the optimizer/EMA update on
    # steps with non-finite loss or grad norm. On by default: for clean
    # runs the guarded step is numerically identical to the unguarded one.
    anomaly_guard: bool = True
    # > 0: additionally flag steps whose loss exceeds factor × a running
    # EMA of accepted losses (e.g. 10.0). Off by default — unlike the
    # non-finite check it can fire on legitimate loss spikes.
    loss_spike_factor: float = 0.0
    # Consecutive anomalous steps before the Trainer rolls back to the last
    # good checkpoint (with a reseeded RNG so the replayed window draws
    # different noise/timesteps).
    max_anomaly_strikes: int = 3
    # Rollback budget: after this many rollbacks the run aborts loudly
    # instead of thrashing between a poisoned basin and the checkpoint.
    max_rollbacks: int = 2
    # Remat override for the TRAINING build of the model: '' (default) =
    # inherit model.remat; otherwise one of model.remat's values
    # (False/'none', True/'full', 'dots') applied to the XUNet blocks
    # for the train step only. Lets one config train with
    # rematerialization (activation memory bound) while sampling/serving
    # build the same checkpoint-compatible model without it (forward-only
    # paths gain nothing from remat) — the remat/donation tuning knob of
    # ROADMAP item 5.
    remat: Any = ""
    # --- hang/stall robustness (docs/DESIGN.md "Stall recovery") ---
    # Heartbeat watchdog over the run's phases (utils/watchdog.py).
    watchdog: WatchdogConfig = dataclasses.field(
        default_factory=WatchdogConfig)
    # Per-layer-group numerics observatory (obs/numerics.py): read-only,
    # bitwise-neutral, zero-recompile stats over the train step.
    numerics: NumericsConfig = dataclasses.field(
        default_factory=NumericsConfig)
    # `nvs3d train --supervise` restart budget: the supervisor restarts a
    # crashed or watchdog-stalled child (resuming via the checkpoint
    # integrity walk-back) at most this many times, with exponential
    # backoff, then gives up loudly.
    max_restarts: int = 3
    # Resolution ladder (train/ladder.py; ROADMAP item 5): '' = off (one
    # flat run at data.img_sidelength for num_steps). Otherwise a
    # comma-separated `res:steps` schedule, e.g. "64:20000,128:10000" —
    # progressive training that runs each rung at its resolution for its
    # step count against ONE checkpoint_dir (the fully-convolutional
    # XUNet keeps an identical param tree at every resolution, PROVIDED
    # model.attn_resolutions selects the same UNet levels at every rung
    # — validate() enforces this). Rung
    # boundaries are canonical checkpoint boundaries (forced save), rung
    # selection on resume derives from the restored step alone, and
    # mid-rung resume is bit-identical to an uninterrupted rung. The
    # promotion gate probes at EVERY rung resolution
    # (registry/gate.run_gate_matrix). Overrides train.num_steps with the
    # schedule's cumulative total.
    ladder: str = ""


@dataclasses.dataclass(frozen=True)
class BrownoutConfig:
    """Serving brownout ladder (docs/DESIGN.md "Serving survivability").

    Two pressure signals — queue depth (queued, undispatched requests)
    and step debt (denoise steps still owed to the ring + queue) — drive
    a three-level ladder evaluated at admission time:

      level 0 (serving)  admit normally;
      level 1 (degraded) admit, but cap trajectory requests' bank window
                         at `k_cap` and their frame count at
                         `max_frames_cap` (cheaper orbits, full refusal
                         not yet needed);
      level 2 (shedding) reject with a structured retryable reason
                         (`Rejected.retryable=True`, `retry_after_s`)
                         BEFORE the hard queue-full backstop.

    A threshold of 0 disables that signal/level; all four at 0 (the
    default) disables the ladder entirely. Transitions are logged
    (events.csv `brownout` rows) and exported as the
    `nvs3d_brownout_level` gauge."""

    # Level-1 (degrade) thresholds: queued requests / owed denoise steps.
    queue_soft: int = 0
    debt_soft: int = 0
    # Level-2 (shed) thresholds. Must be >= the soft ones when both set.
    queue_hard: int = 0
    debt_hard: int = 0
    # Degraded-admission caps for trajectory requests (0 = leave as
    # requested). Applied at admission, so an in-flight orbit never
    # changes shape mid-ring.
    k_cap: int = 0
    max_frames_cap: int = 0
    # Hint returned with level-2 rejects: how long the client should
    # back off before retrying.
    retry_after_s: float = 0.25


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Serving SLOs (obs/slo.py; docs/DESIGN.md "Request tracing, SLOs
    & flight recorder"): declarative per-step-class latency objectives
    scored live against every completed request, with multi-window
    burn-rate breach detection (`nvs3d_slo_*` gauges, `slo_breach`
    events)."""

    # Per-step-class latency budgets: "<steps>:<latency_ms>,..." e.g.
    # "4:500,64:2000" — a 4-step request owes a response in 500 ms.
    # Requests are scored against the smallest class covering their
    # step count. "" (default) disables the engine entirely.
    targets: str = ""
    # Availability objective per class: the fraction of requests that
    # must meet their latency budget (and succeed at all).
    objective: float = 0.99
    # Multi-window burn-rate alerting: a breach needs BOTH the fast
    # window burning above fast_burn (paging-fast, noisy alone) AND the
    # slow window above slow_burn (sustained, slow alone). The default
    # thresholds are the standard 14x/2x pairing for a 99% objective.
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    fast_burn: float = 14.0
    slow_burn: float = 2.0


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Sampling-service front-end (sample/service.py; `nvs3d serve`).

    The service coalesces concurrent requests into padded batches at
    power-of-two bucket sizes and dispatches each bucket through an LRU
    cache of compiled sampler programs — warm traffic never recompiles
    (docs/DESIGN.md "Serving")."""

    # Scheduler: 'step' (default) = persistent stepper with STEP-LEVEL
    # continuous batching — one compiled denoise-step program per bucket
    # shape runs over a ring of active request slots; new arrivals join
    # the ring between steps and finished rows exit immediately, so a
    # 4-step distilled request never waits behind a 256-step one, and
    # requests with different step counts / guidance weights share one
    # program (t and w are device arguments, not compile-time constants).
    # 'request' = the PR 3 whole-request dispatcher (one lax.scan per
    # coalesced group), kept as the serve_bench baseline and for exact
    # dpm++ 2M serving — the stepper serves dpm++ with the first-order
    # (history-free) update, same rule as the stochastic sampler.
    scheduler: str = "step"
    # Largest coalesced batch (top of the power-of-two bucket ladder).
    max_batch: int = 8
    # Bounded request queue: a submit past this depth is REJECTED with a
    # reason (events.csv `reject` row) instead of growing latency unboundedly.
    queue_depth: int = 64
    # How long the batcher holds the oldest queued request open for
    # co-riders before dispatching a partial bucket. 0 = dispatch
    # immediately (no coalescing beyond what is already queued).
    flush_timeout_ms: float = 10.0
    # Default per-request queue-wait deadline; a request still undispatched
    # past it is rejected (deadline_exceeded). 0 = no deadline. Requests
    # can override per call.
    default_deadline_ms: float = 0.0
    # LRU capacity of the sampler-program cache, in (bucket, sampler
    # config) entries. Each entry holds a compiled XLA program.
    program_cache_entries: int = 8
    # Respaced reverse-process steps for served requests; 0 = use
    # diffusion.sample_timesteps.
    sample_steps: int = 0
    # Serving precision (sample/precision.py): what the service/watcher
    # put ON DEVICE at weight-stage time. 'float32' = weights as
    # published (exact, the default); 'bfloat16' = every float leaf cast
    # to bf16 (half the HBM residency/transfer, flax promotes on-chip);
    # 'int8' = per-channel symmetric weight-only int8 for conv/dense
    # kernels with f32 scales, bf16 elsewhere — the sampler program
    # dequantizes in-jit so weights REST quantized. The program-cache
    # key folds precision in, and the registry gate probes candidates AT
    # this precision so quantization loss counts against
    # registry.gate_margin_db. int8 requires registry staging: the
    # quantized deployment must serve gate-probed registry versions
    # (`nvs3d serve --registry`), never raw checkpoints.
    precision: str = "float32"
    # Trajectory serving (docs/DESIGN.md "Trajectory serving & stochastic
    # conditioning"): per-ring-slot FRAME BANK capacity — the device-
    # resident (k_max, H, W, C) buffer of clean frames each trajectory
    # request conditions on (a random bank view per denoise step, the
    # 3DiM stochastic-conditioning protocol, drawn in-jit from the
    # slot's PRNG carry). 0 (default) disables trajectory serving
    # entirely: the stepper runs the exact pre-bank program, so
    # single-shot serving is bit-identical to a build without this
    # feature (zero-cost when unused). > 0 requires scheduler='step'
    # (the whole-request dispatcher has no ring for frames to re-enter).
    # k_max is part of the stepper program SHAPE, so one service serves
    # one k_max — mixed single-shot + trajectory traffic still compiles
    # one program per bucket (per-request banks smaller than k_max ride
    # the same arrays with a lower effective window).
    k_max: int = 0
    # Upper bound on poses per TrajectoryRequest (backpressure for
    # orbit-sized requests: a 10k-frame request is a typo, not a load).
    max_frames: int = 64
    # Where the service writes its events.csv (rejections, deadline
    # expiries) — same schema as the trainer's.
    results_folder: str = "./serve"
    # --- survivability (docs/DESIGN.md "Serving survivability") ---
    # Graceful drain: on SIGTERM/SIGINT (`nvs3d serve`) or
    # SamplingService.drain(), new admissions are rejected with a
    # structured retryable reason while queued + in-ring work finishes;
    # past this budget the leftovers fail retryably and the worker stops.
    drain_timeout_s: float = 30.0
    # Worker supervisor (the serving analogue of `nvs3d train
    # --supervise`): a died worker thread is restarted with exponential
    # backoff at most this many times per service lifetime; undispatched
    # requests stay queued across the restart, in-flight ring rows fail
    # retryably. 0 disables restarts (a worker death stops the service).
    max_worker_restarts: int = 3
    # First-restart backoff; doubles per consecutive restart (capped at
    # 30 s). Small default: serving restarts race an SLO, not a
    # checkpoint restore.
    worker_backoff_s: float = 0.05
    # In-ring anomaly quarantine: consecutive non-finite steps (the
    # per-row device-side finite mask) a slot survives before it is
    # evicted and its ticket failed with SampleAnomaly. NaN never heals
    # under further denoising, so 1 (evict on first strike) is right for
    # production; > 1 exists for drills/diagnosis.
    anomaly_strikes: int = 1
    # stop()'s worker-join budget: past it the service writes a
    # stall-style all-thread-stacks diagnosis and raises instead of
    # silently leaking a wedged thread (PR 2 watchdog convention).
    stop_timeout_s: float = 10.0
    # Conditioning cache (docs/DESIGN.md "Conditioning cache & fused
    # serving attention"): compute XUNet's conditioning branch — the
    # per-level pose/FiLM embeddings and the cond-frame stem features,
    # which never change within a request — ONCE at admission (once per
    # frame-bank encode for trajectories) instead of inside every
    # denoise step. The activations live device-resident on the ring
    # slot alongside z/keys/banks and enter the step program as device
    # arguments, so program identity stays bucket/shape-only; the CFG
    # uncond (cond_mask=0) half is cached globally per (H, W) — it is
    # pose-independent — so guidance pairs share one encode, and a hot
    # swap invalidates it (in-flight slots die with the drain, pinned
    # to their start version). False (default) keeps the in-jit encode;
    # True requires scheduler='step'. Cached and uncached programs are
    # bit-identical single-key (tests/test_cond_cache.py).
    cond_cache: bool = False
    # Minimum wall-clock per ring dispatch, milliseconds (0 = off). After
    # the device work of a dispatch completes, the worker sleeps out the
    # residual — a PACING floor, not a slowdown of the device program.
    # Two uses: (a) rate-limiting a replica that shares a host with
    # latency-sensitive neighbors; (b) fleet drills on few-core CI hosts,
    # where N CPU replicas otherwise contend for the same core and a
    # router scaling lane measures scheduler noise instead of dispatch
    # overlap — the sleep releases the GIL/core, emulating N device-bound
    # replicas honestly (tools/serve_bench.py --fleet records the floor
    # it ran with in the artifact).
    step_floor_ms: float = 0.0
    # Brownout degradation ladder (off by default).
    brownout: BrownoutConfig = dataclasses.field(
        default_factory=BrownoutConfig)
    # Per-step-class latency SLOs + burn-rate alerting (off by default).
    slo: SLOConfig = dataclasses.field(default_factory=SLOConfig)


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Fleet router (serve/router.py; `nvs3d route`; docs/DESIGN.md
    "Fleet serving").

    A thin front-end that spreads traffic over N SamplingService
    replicas: least-step-debt dispatch fed by each replica's /healthz
    gauges, session affinity for trajectory orbits, transparent failover
    on death/drain/retryable rejection, and registry-channel rolling
    deploys gated on SLO burn + swap-breaker state."""

    # Health-poll period for the background poller (seconds). Between
    # polls the router tracks its own outstanding-steps delta per
    # replica, so dispatch pressure is poll-fresh + local-accurate.
    health_poll_s: float = 0.5
    # A polled snapshot older than this is STALE: the replica is treated
    # as unknown-health (dispatchable only if nothing fresh is) rather
    # than trusted at its last-known debt.
    health_ttl_s: float = 5.0
    # Failover budget PER REQUEST: how many times a request may be
    # re-routed (replica died, drained, or shed retryably) before the
    # router gives up and surfaces the structured error to the caller.
    # Distinct from sample/client.submit_with_retry's retries: that loop
    # re-asks the SAME endpoint later; this budget moves the request
    # ACROSS replicas now.
    retry_budget: int = 3
    # When EVERY eligible replica sheds (fleet-wide brownout) the router
    # does NOT burn the retry budget spinning across replicas — it
    # raises FleetSaturated (retryable, carrying the fleet's max
    # retry_after_s) after this many full-fleet sweeps.
    saturation_sweeps: int = 1
    # Session-affinity table capacity (orbit sessions pinned to the
    # replica holding their frame bank); oldest entries evict first.
    affinity_entries: int = 1024
    # --- rolling deploy (serve/deploy.py; `nvs3d route deploy`) ---
    # Per-replica router-level drain budget: out-of-rotation wait for
    # step_debt+queue_depth to hit zero before the channel poke.
    deploy_drain_timeout_s: float = 30.0
    # Post-swap probation: the canary serves back in rotation this long
    # while the gate watches its SLO fast-burn and swap breaker.
    deploy_probation_s: float = 2.0
    # Gate threshold: probation fails when the replica's fast-window SLO
    # burn rate reaches this (default = the fast-window page threshold,
    # SLOConfig.fast_burn).
    deploy_burn_max: float = 14.0
    # Budget for a poked replica to report the target model_version
    # before the deploy declares the swap failed and rolls back.
    deploy_swap_timeout_s: float = 30.0
    # --- self-healing fleet (docs/DESIGN.md "Fleet survivability") ---
    # Virtual nodes per replica on the consistent-hash affinity ring.
    # More vnodes = smoother key spread; the ring is rebuilt only when
    # the replica SET changes, so this is a startup cost.
    affinity_vnodes: int = 64
    # Hedged dispatch for stateless singles: if the first replica has
    # not answered after this long, a second copy goes to the next
    # replica on the ring; first response wins, the loser is abandoned
    # (recorded as a `router_hedge` span). 0 disables hedging.
    # Trajectories never hedge — their frame bank is single-homed.
    hedge_delay_s: float = 0.0
    # Per-hop timeout budget (seconds): one replica attempt may consume
    # at most this much of the request's total timeout before the
    # router abandons the hop and fails over — a wedged replica can
    # never eat the whole client deadline. 0 = no per-hop bound (the
    # request timeout is the only clock).
    hop_timeout_s: float = 0.0
    # Gray-failure demotion: a replica whose polled latency_p99_s is
    # >= this factor x the fleet's BEST fresh p99 is demoted — it only
    # receives dispatches when no un-demoted replica is eligible.
    # 0 disables (PR 16 behavior).
    demote_p99_factor: float = 0.0
    # Router journal (serve/journal.py): a full outstanding-steps
    # snapshot row is appended every N hop records so replay cost stays
    # bounded. The journal itself is enabled by passing journal= to
    # FleetRouter (or `journal` in the router_main spec).
    journal_snapshot_every: int = 32
    # --- fleet supervisor (serve/fleet_supervisor.py) ---
    # Restart budget PER SLOT; exhaustion marks the slot failed loudly
    # (replica_giveup event) instead of flapping forever.
    supervisor_max_restarts: int = 3
    # Exponential restart backoff base / cap (PR 2 discipline:
    # min(cap, backoff_s * 2**(restarts-1))).
    supervisor_backoff_s: float = 1.0
    supervisor_backoff_cap_s: float = 60.0
    # A replica whose ready-file heartbeat is older than this is WEDGED
    # (the process is alive but its event loop stopped beating).
    supervisor_heartbeat_max_age_s: float = 15.0
    # Consecutive /healthz failures before a live process is declared
    # wedged (transient poll misses must not trigger a restart).
    supervisor_health_fails: int = 3
    # Supervisor monitor-loop period (seconds).
    supervisor_poll_s: float = 1.0
    # Budget for a restarted replica to write its ready file AND answer
    # /healthz with the expected version before the resurrection is
    # declared failed (burning one restart from the budget).
    supervisor_ready_timeout_s: float = 300.0


@dataclasses.dataclass(frozen=True)
class DistillConfig:
    """Progressive distillation (train/distill.py; `nvs3d distill`).

    Salimans & Ho 2022 (arXiv 2202.00512): each round trains a student —
    initialized from the teacher — to match TWO teacher DDIM steps with
    ONE of its own, halving the sampling-step count per round
    (start_steps → start_steps/2 → … → target_steps). The registry is
    the teacher/student store: the teacher is read from a channel, each
    student generation is published as a version, and promotion runs the
    existing fixed-seed PSNR gate (registry/gate.py)."""

    # Step count of the first teacher (respaced from diffusion.timesteps).
    start_steps: int = 256
    # Stop once the student reaches this many sampling steps. Must divide
    # start_steps by a power of two (one halving per round).
    target_steps: int = 4
    # Optimizer updates per halving round.
    steps_per_round: int = 200
    # Distillation batch size (host-assembled; single-device).
    batch_size: int = 8
    lr: float = 1e-4
    # Truncated-SNR loss-weight cap: weight = clip(SNR, 1, snr_clip) on
    # the x₀-space distillation loss (the paper's max(SNR, 1), bounded so
    # near-clean timesteps cannot dominate a round).
    snr_clip: float = 5.0
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class RegistryConfig:
    """Model lifecycle registry (novel_view_synthesis_3d_tpu/registry/;
    docs/DESIGN.md "Model lifecycle").

    A content-hashed, versioned store of publishable model snapshots with
    channel pointers (`latest` = newest published, `stable` = quality-
    gated): the trainer PUBLISHES to `latest` every `publish_every` steps,
    `nvs3d registry promote` runs the PSNR gate and advances `stable`, and
    a serving process subscribed to a channel HOT-RELOADS the new params
    under live traffic (sample/service.py swap path)."""

    # Registry root directory (one dir per version under <dir>/versions).
    dir: str = "./registry"
    # Trainer hook cadence: every N steps the EMA snapshot (params when
    # EMA is off) is published to the `latest` channel without blocking
    # the step loop. 0 = trainer never publishes.
    publish_every: int = 0
    # Publish the EMA tree when the run trains one (it is what you sample
    # with); False forces raw params.
    publish_ema: bool = True
    # Channel a serving process subscribes to (`nvs3d serve --registry`);
    # production serves `stable`, canaries can ride `latest`.
    channel: str = "stable"
    # Reload-watcher poll period (seconds) for the serving subscription.
    poll_s: float = 2.0
    # Quality gate: a candidate may regress the fixed-seed PSNR probe vs
    # the incumbent by at most this many dB before promotion is refused
    # (gate_fail event + non-zero exit; the stable pointer never moves).
    gate_margin_db: float = 0.5
    # Respaced reverse-process steps for the gate's PSNR probe (small on
    # purpose: the gate is a regression tripwire, not a benchmark).
    gate_sample_steps: int = 8
    # Probe batch rows scored by the gate.
    gate_batch: int = 4
    # Multi-view consistency gate (eval/metrics.multi_view_consistency):
    # when > 0, `nvs3d registry promote` and the distill auto-promote
    # ALSO probe adjacent-frame PSNR over a fixed autoregressive orbit
    # of this many frames (stochastic conditioning, fixed seed), and a
    # candidate regressing that metric beyond gate_margin_db is refused
    # — distilled/quantized models are gated on TRAJECTORY quality, not
    # just single-frame PSNR. 0 (default) = single-frame gate only.
    # Needs >= 2 frames for an adjacent pair.
    gate_trajectory_frames: int = 0
    # Fixed probe seed: candidate and incumbent see identical noise.
    gate_seed: int = 0
    # `registry gc` retention: keep the newest K versions (channel-pinned
    # versions are always kept).
    keep: int = 5


@dataclasses.dataclass(frozen=True)
class ObsProfileConfig:
    """Continuous profiling windows (obs/profiler.py; docs/DESIGN.md
    "Performance observatory"): periodically re-arm a bounded
    jax.profiler window, attribute the captured device time to the
    shared op-group vocabulary, and land profile_window rows +
    nvs3d_group_device_time_seconds gauges. Host-side only — bitwise
    outputs and compile identity are unchanged; window-armed steps are
    excluded from the step-rate gauges. On by default: the defaults
    amortize to well under the 1% overhead contract (one ~2-step window
    per 500 steps), and tiny test runs never reach the first cadence."""

    enabled: bool = True
    # Training cadence: arm a window every N steps (window covers
    # [N, N + window_steps) etc.). 0 disables the training profiler.
    every_steps: int = 500
    # Steps per window. Short on purpose: a window prices ~window/every
    # in excluded step-rate samples plus the host-side parse.
    window_steps: int = 2
    # Serving cadence, counted in dispatches (SamplingService.dispatches
    # spans ring steps and batched dispatches). 0 disables in serving.
    serve_every_dispatches: int = 2000
    serve_window_dispatches: int = 2


@dataclasses.dataclass(frozen=True)
class ObsConfig:
    """Unified telemetry layer (novel_view_synthesis_3d_tpu/obs/;
    docs/DESIGN.md "Observability"): span tracing with Perfetto export,
    the metrics registry + sinks, and utilization gauges. Everything here
    is host-side — no jitted code changes, zero new recompiles."""

    # Master switch. False: NullTracer, no JSONL, no device polling, no
    # endpoint — the legacy metrics.csv/events.csv still write (they are
    # the run's primary record, not optional telemetry).
    enabled: bool = True
    # Span tracing: collect trainer/serving phase spans and export
    # <results_folder>/trace.json (Chrome-trace JSON, Perfetto-loadable)
    # at the end of the run.
    trace: bool = True
    # Bounded span buffer: a million-step run keeps the most recent spans
    # and counts the rest as dropped instead of growing host memory.
    trace_max_events: int = 200_000
    # Prometheus text-exposition endpoint (/metrics + /healthz, stdlib
    # http.server). 0 (default) = no socket is ever opened; set a port to
    # serve from `nvs3d train` and `nvs3d serve`.
    metrics_port: int = 0
    # Bind address for the endpoint. 127.0.0.1 by default — an
    # unauthenticated scrape target must not face the network; scrape
    # remotely over an SSH tunnel (docs/TPU_VM_SETUP.md).
    metrics_host: str = "127.0.0.1"
    # telemetry.jsonl sink: machine-readable span/gauge/event stream in
    # the results folder (tools/summarize_bench.py reads it).
    jsonl: bool = True
    # Size cap on telemetry.jsonl: past this many MB the file rotates
    # aside to telemetry.jsonl.old (one generation kept, the events.csv
    # stale-schema convention) so a multi-day serve run cannot fill the
    # disk. 0 = unbounded.
    telemetry_max_mb: float = 256.0
    # Device-memory poll period (seconds) for the bytes-in-use/peak/limit
    # gauges; 0 disables the monitor thread.
    device_poll_s: float = 10.0
    # On-demand jax.profiler window over the step range [a, b): XProf
    # captures line up with span timestamps. (0, 0) = off. Complements
    # train.profile_from/profile_steps (kept for back-compat).
    xprof_steps: Tuple[int, int] = (0, 0)
    # One-time jit(...).lower().cost_analysis() FLOPs estimate of the
    # train step, feeding the MFU / imgs-per-sec gauges and the mfu
    # column in metrics.csv. Costs one extra trace (no XLA compile) at
    # startup.
    cost_analysis: bool = True
    # Continuous per-op-group profiling windows (obs/profiler.py).
    profile: ObsProfileConfig = dataclasses.field(
        default_factory=ObsProfileConfig)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device mesh for distributed execution (replaces reference pmap, §2.3).

    Axes: 'data' = DP (batch sharding, psum over ICI emitted by XLA),
    'model' = reserved for TP, 'seq' = ring-attention sequence parallelism.
    """

    data: int = -1  # -1 = all remaining devices
    model: int = 1
    seq: int = 1
    # Pipeline parallelism: partition the XUNet's block sequence into this
    # many stages placed along the 'model' axis (parallel/pipeline.py).
    # stages>1 runs the train.grad_accum_steps microbatches through a
    # GPipe-style fill/drain schedule with ppermute stage handoff, so the
    # model's activations (and its stage params inside the step) scale past
    # one chip. Requires mesh.model == stages and is mutually exclusive
    # with tensor parallelism / sequence parallelism / fsdp.
    stages: int = 1


@dataclasses.dataclass(frozen=True)
class Config:
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    diffusion: DiffusionConfig = dataclasses.field(default_factory=DiffusionConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    obs: ObsConfig = dataclasses.field(default_factory=ObsConfig)
    registry: RegistryConfig = dataclasses.field(
        default_factory=RegistryConfig)
    distill: DistillConfig = dataclasses.field(
        default_factory=DistillConfig)
    router: RouterConfig = dataclasses.field(
        default_factory=RouterConfig)

    # ------------------------------------------------------------------
    # Validation
    # ------------------------------------------------------------------
    def validate(self) -> "Config":
        """Cross-field sanity checks with actionable messages.

        Catches the mistakes that otherwise surface as opaque errors deep
        inside flax/XLA (e.g. GroupNorm's 32-group divisibility failing as
        a reshape error three modules down). Returns self so call sites can
        chain. Enum-valued fields (loss, objective, sampler, remat, …) are
        checked at their point of use, where the full context lives.
        """
        m, d, t = self.model, self.data, self.train
        errors = []
        if m.ch <= 0 or not m.ch_mult:
            errors.append("model.ch must be positive and model.ch_mult "
                          "non-empty")
        for level, mult in enumerate(m.ch_mult):
            c = m.ch * mult
            if c % 32 != 0:
                errors.append(
                    f"model.ch×mult = {c} is not divisible by 32 "
                    "(GroupNorm runs with 32 groups at every level)")
            # Heads only matter at levels where attention actually runs.
            if (d.img_sidelength // (2 ** level) in m.attn_resolutions
                    and c % m.attn_heads != 0):
                errors.append(
                    f"model.ch×mult = {c} (level {level}, attention "
                    f"resolution {d.img_sidelength // (2 ** level)}) is "
                    f"not divisible by attn_heads={m.attn_heads}")
        # Cross-frame attention is the ONLY path from the conditioning
        # image to the target frame (convs are per-frame). A non-empty
        # attn_resolutions that matches NO UNet level silently trains an
        # unconditional pose-memorizer: seen-pose metrics look great,
        # held-out eval sits at the mean-image floor (r2/r3 quality-run
        # postmortem — the r2 tool used size//4 on a 2-level UNet).
        level_res = {d.img_sidelength // (2 ** lv)
                     for lv in range(len(m.ch_mult))}
        stray = set(m.attn_resolutions) - level_res
        if m.attn_resolutions and stray == set(m.attn_resolutions):
            errors.append(
                f"model.attn_resolutions={tuple(m.attn_resolutions)} "
                f"matches NO UNet level (levels run at "
                f"{tuple(sorted(level_res, reverse=True))} for "
                f"data.img_sidelength={d.img_sidelength}, "
                f"{len(m.ch_mult)} levels): cross-frame attention would "
                "never fire and the conditioning image could not influence "
                "the generated view. Pick resolutions from the level set, "
                "or set attn_resolutions=() explicitly for an attention-free "
                "model")
        elif stray:
            # Partial match: attention fires somewhere, but stray entries
            # are silently inert (advisor r3 — a sub-lethal recurrence of
            # the r2/r3 postmortem class). Entries related to the
            # sidelength by a power of two are a deliberate DDPM-style
            # superset list (the presets keep one attn list across depths
            # and image sizes; e.g. 8 on a 3-level 64px UNet) — allowed.
            # Anything else (e.g. 5 at sidelength 16) can never name a
            # UNet level at any depth or power-of-two rescale of this
            # config: error.
            def _pow2_related(e: int) -> bool:
                if e <= 0:
                    return False
                a, b = max(e, d.img_sidelength), min(e, d.img_sidelength)
                q, r = divmod(a, b)
                return r == 0 and (q & (q - 1)) == 0
            bogus = {e for e in stray if not _pow2_related(e)}
            if bogus:
                errors.append(
                    f"model.attn_resolutions entries "
                    f"{tuple(sorted(bogus))} match no UNet level and never "
                    f"could (level resolutions are "
                    f"data.img_sidelength={d.img_sidelength} divided by "
                    "powers of 2): each would be silently inert. Remove "
                    "them or pick resolutions from the level set")
        if not 0.0 <= m.dropout < 1.0:
            errors.append(f"model.dropout={m.dropout} outside [0, 1)")
        if m.num_cond_frames < 1:
            errors.append("model.num_cond_frames must be >= 1")
        down = 2 ** (len(m.ch_mult) - 1)
        if d.img_sidelength % down != 0:
            errors.append(
                f"data.img_sidelength={d.img_sidelength} is not divisible "
                f"by 2^{len(m.ch_mult) - 1} (the UNet downsamples "
                f"{len(m.ch_mult) - 1} times)")
        if self.diffusion.timesteps < 1:
            errors.append("diffusion.timesteps must be >= 1")
        if not 1 <= self.diffusion.sample_timesteps <= self.diffusion.timesteps:
            errors.append(
                f"diffusion.sample_timesteps="
                f"{self.diffusion.sample_timesteps} must be in "
                f"[1, diffusion.timesteps={self.diffusion.timesteps}]")
        if t.eval_every > 0 and not (
                1 <= t.eval_sample_steps <= self.diffusion.timesteps):
            # Only enforced when the probe is on: eval_sample_steps is inert
            # otherwise, and a direct eval_step() call still gets a clear
            # error from sampling_schedule/respace.
            errors.append(
                f"train.eval_sample_steps={t.eval_sample_steps} must be in "
                f"[1, diffusion.timesteps={self.diffusion.timesteps}] when "
                "train.eval_every is set")
        if t.batch_size < 1:
            errors.append("train.batch_size must be >= 1")
        if self.data.samples_per_instance < 1:
            errors.append(
                f"data.samples_per_instance={self.data.samples_per_instance}"
                " must be >= 1")
        elif t.batch_size % self.data.samples_per_instance != 0:
            # Each index draw contributes samples_per_instance consecutive
            # batch slots (reference data_loader.py:183-195 semantics).
            errors.append(
                f"train.batch_size={t.batch_size} must be a multiple of "
                f"data.samples_per_instance="
                f"{self.data.samples_per_instance}")
        spd = t.steps_per_dispatch
        if spd < 1:
            errors.append(
                f"train.steps_per_dispatch={spd} must be >= 1")
        elif spd > 1:
            if t.num_steps % spd:
                errors.append(
                    f"train.num_steps={t.num_steps} must be a multiple of "
                    f"train.steps_per_dispatch={spd} (the loop advances "
                    "K steps per dispatch)")
            for nm in ("log_every", "save_every", "eval_every",
                       "sample_every"):
                v = getattr(t, nm)
                if v and v % spd:
                    errors.append(
                        f"train.{nm}={v} must be a multiple of "
                        f"train.steps_per_dispatch={spd} — the trainer only "
                        "observes step counts at dispatch boundaries, so a "
                        "misaligned cadence would silently never fire")
            if t.profile_steps and (t.profile_from % spd
                                    or t.profile_steps % spd):
                errors.append(
                    f"train.profile_from={t.profile_from}/profile_steps="
                    f"{t.profile_steps} must be multiples of "
                    f"train.steps_per_dispatch={spd}")
        if t.optimizer not in ("adam", "adafactor"):
            errors.append(
                f"train.optimizer={t.optimizer!r} must be 'adam' "
                "(reference, train.py:46) or 'adafactor' (memory-lean: "
                "factored second moments, no first moment)")
        if t.grad_accum_steps > 1 and t.loss == "frobenius":
            # Lifted out of train/step.make_train_step: the whole-tensor L2
            # norm is not decomposable across micro-batches (mean of micro
            # norms != full-batch norm), so accumulation would silently
            # change the reference-parity objective. Failing here costs
            # nothing; failing at step-build time costs the compile.
            errors.append(
                f"train.grad_accum_steps={t.grad_accum_steps} > 1 requires "
                "train.loss='mse' — the 'frobenius' whole-tensor norm has "
                "no per-micro-batch decomposition")
        if t.update_sharding not in ("replicated", "zero"):
            errors.append(
                f"train.update_sharding={t.update_sharding!r} must be "
                "'replicated' or 'zero' (ZeRO-style sharded Adam+EMA "
                "update, parallel/zero.py)")
        elif t.update_sharding == "zero":
            if t.optimizer != "adam":
                errors.append(
                    "train.update_sharding='zero' requires "
                    f"train.optimizer='adam' (got {t.optimizer!r}) — the "
                    "sharded update flattens optimizer moments per leaf, "
                    "which breaks adafactor's factored row/col stats")
            if t.fsdp:
                errors.append(
                    "train.update_sharding='zero' conflicts with "
                    "train.fsdp=True: fsdp already shards params + "
                    "optimizer state over 'data'; pick one")
        if t.adam_mu_dtype not in ("float32", "bfloat16"):
            errors.append(
                f"train.adam_mu_dtype={t.adam_mu_dtype!r} must be "
                "'float32' or 'bfloat16'")
        if t.probe_dtype not in ("", "float32", "bfloat16"):
            errors.append(
                f"train.probe_dtype={t.probe_dtype!r} must be '' (param "
                "dtype), 'float32', or 'bfloat16'")
        if t.remat not in ("", False, True, "none", "full", "dots"):
            errors.append(
                f"train.remat={t.remat!r} must be '' (inherit "
                "model.remat), False/'none', True/'full', or 'dots' — it "
                "overrides the checkpoint policy over XUNet blocks for "
                "the training build only")
        if t.ema_host and t.ema_decay <= 0:
            errors.append(
                "train.ema_host=True is inert without train.ema_decay > 0")
        if t.ema_host_every < 1:
            errors.append(
                f"train.ema_host_every={t.ema_host_every} must be >= 1")
        if not 0.0 <= t.cond_drop_prob <= 1.0:
            errors.append(
                f"train.cond_drop_prob={t.cond_drop_prob} outside [0, 1]")
        if t.loss_spike_factor != 0 and t.loss_spike_factor <= 1.0:
            errors.append(
                f"train.loss_spike_factor={t.loss_spike_factor} must be 0 "
                "(off) or > 1 — a factor <= 1 would flag ordinary steps "
                "whose loss sits at or above its own running mean")
        if t.max_anomaly_strikes < 1:
            errors.append(
                f"train.max_anomaly_strikes={t.max_anomaly_strikes} must "
                "be >= 1")
        if t.max_rollbacks < 0:
            errors.append(
                f"train.max_rollbacks={t.max_rollbacks} must be >= 0")
        if d.max_record_retries < 0:
            errors.append(
                f"data.max_record_retries={d.max_record_retries} must be "
                ">= 0")
        if d.backend not in ("files", "packed"):
            errors.append(
                f"data.backend={d.backend!r} must be 'files' (SRN "
                "PNG/pose tree) or 'packed' (sharded records from "
                "`nvs3d pack`; data.root_dir is the packed corpus dir)")
        if d.backend == "packed" and d.prefetch < 1:
            errors.append(
                f"data.prefetch={d.prefetch} must be >= 1 with "
                "data.backend='packed' (it sizes the pipelined loader's "
                "decode-ahead depth)")
        if d.mix:
            # Mirrors the train.adam_mu_dtype style: structural checks
            # with the semantics in the message — a malformed mix spec
            # must fail at startup, never as a mid-run KeyError.
            if d.backend != "packed":
                errors.append(
                    f"data.mix requires data.backend='packed' (got "
                    f"{d.backend!r}) — the mixer samples across `nvs3d "
                    "pack` corpora, the files backend has no corpus "
                    "identity")
            seen_names = set()
            for entry in d.mix.split(","):
                parts = entry.strip().split(":", 2)
                if len(parts) != 3 or not all(p.strip() for p in parts):
                    errors.append(
                        f"data.mix entry {entry.strip()!r} must be "
                        "'name:weight:path' (e.g. "
                        "'cars:3:/data/cars_packed')")
                    continue
                name, weight, _path = (p.strip() for p in parts)
                if name in seen_names:
                    errors.append(
                        f"data.mix names corpus {name!r} twice — names "
                        "key the per-corpus metrics and must be unique")
                seen_names.add(name)
                try:
                    w = float(weight)
                except ValueError:
                    w = -1.0
                if w <= 0:
                    errors.append(
                        f"data.mix corpus {name!r} has weight "
                        f"{weight!r} — must be a number > 0 (weights "
                        "are relative sampling odds, normalized over "
                        "the mix)")
        if m.num_classes < 0:
            errors.append(
                f"model.num_classes={m.num_classes} must be >= 0 (0 = no "
                "category conditioning, > 0 sizes the zero-init category "
                "embedding table)")
        if t.ladder:
            # Same loud-at-startup contract as data.mix above.
            rungs = []
            for entry in t.ladder.split(","):
                parts = entry.strip().split(":")
                if len(parts) != 2:
                    errors.append(
                        f"train.ladder entry {entry.strip()!r} must be "
                        "'resolution:steps' (e.g. '64:20000,128:10000')")
                    continue
                try:
                    res, steps = int(parts[0]), int(parts[1])
                except ValueError:
                    errors.append(
                        f"train.ladder entry {entry.strip()!r} must be "
                        "two integers 'resolution:steps'")
                    continue
                if res < 8 or res & (res - 1) != 0:
                    errors.append(
                        f"train.ladder resolution {res} must be a power "
                        "of two >= 8 (the UNet downsample chain halves "
                        "H/W per level)")
                if steps < 1:
                    errors.append(
                        f"train.ladder rung {entry.strip()!r} must train "
                        "for >= 1 step")
                rungs.append(res)
            if rungs != sorted(rungs):
                errors.append(
                    f"train.ladder={t.ladder!r} resolutions must be "
                    "non-decreasing — the ladder is progressive "
                    "low-to-high (64 before 128)")
            # The rung param trees must be STRUCTURALLY identical (one
            # checkpoint spans the ladder). Conv/norm shapes are
            # resolution-free, but model.attn_resolutions is keyed on
            # absolute feature-map resolution — if it selects different
            # UNet LEVELS at different rung resolutions, the trees
            # diverge (AttnBlock params appear under different blocks).
            patterns = {
                res: tuple(
                    lvl for lvl in range(len(m.ch_mult))
                    if (res >> lvl) in m.attn_resolutions)
                for res in sorted(set(rungs))}
            if len(set(patterns.values())) > 1:
                errors.append(
                    f"train.ladder={t.ladder!r} places attention at "
                    "different UNet levels per rung "
                    f"({ {r: list(p) for r, p in patterns.items()} }): "
                    "model.attn_resolutions is keyed on absolute "
                    "feature-map resolution, so the rung param trees "
                    "would be structurally incompatible — choose "
                    "attn_resolutions that select the SAME levels at "
                    "every rung resolution (e.g. [] to disable "
                    "attention for the ladder run)")
        if t.max_restarts < 0:
            errors.append(
                f"train.max_restarts={t.max_restarts} must be >= 0")
        nc = t.numerics
        if nc.every < 1:
            errors.append(
                f"train.numerics.every={nc.every} must be >= 1 (host-side "
                "decimation period for the per-group stats)")
        if nc.spike_z <= 0:
            errors.append(
                f"train.numerics.spike_z={nc.spike_z} must be > 0 (EWMA "
                "z-score threshold for numerics_spike events)")
        if not 0.0 < nc.ewma_decay < 1.0:
            errors.append(
                f"train.numerics.ewma_decay={nc.ewma_decay} must be in "
                "(0, 1)")
        wd = t.watchdog
        if wd.check_interval_s <= 0:
            errors.append(
                f"train.watchdog.check_interval_s={wd.check_interval_s} "
                "must be > 0")
        for nm in ("data_fetch_s", "step_s", "compile_s",
                   "checkpoint_save_s", "eval_s", "hard_exit_s"):
            if getattr(wd, nm) < 0:
                errors.append(
                    f"train.watchdog.{nm}={getattr(wd, nm)} must be >= 0 "
                    "(0 disables that deadline)")
        sv = self.serve
        if sv.scheduler not in ("step", "request"):
            errors.append(
                f"serve.scheduler={sv.scheduler!r} must be 'step' "
                "(step-level continuous batching) or 'request' (whole-"
                "request dispatch)")
        if sv.max_batch < 1 or (sv.max_batch & (sv.max_batch - 1)) != 0:
            errors.append(
                f"serve.max_batch={sv.max_batch} must be a power of two "
                "(the micro-batcher's bucket ladder is 1, 2, 4, …)")
        if sv.queue_depth < 1:
            errors.append(f"serve.queue_depth={sv.queue_depth} must be >= 1")
        if sv.flush_timeout_ms < 0:
            errors.append(
                f"serve.flush_timeout_ms={sv.flush_timeout_ms} must be >= 0")
        if sv.default_deadline_ms < 0:
            errors.append(f"serve.default_deadline_ms="
                          f"{sv.default_deadline_ms} must be >= 0")
        if sv.program_cache_entries < 1:
            errors.append(
                f"serve.program_cache_entries={sv.program_cache_entries} "
                "must be >= 1")
        if sv.sample_steps < 0 or sv.sample_steps > self.diffusion.timesteps:
            errors.append(
                f"serve.sample_steps={sv.sample_steps} must be in "
                f"[0, diffusion.timesteps={self.diffusion.timesteps}] "
                "(0 = diffusion.sample_timesteps)")
        if sv.precision not in ("float32", "bfloat16", "int8"):
            # Mirrors the train.adam_mu_dtype style: enum membership with
            # the semantics in the message (CLI overrides arrive as raw
            # strings — a typo must fail loudly, not serve f32 silently).
            errors.append(
                f"serve.precision={sv.precision!r} must be 'float32' "
                "(weights as published), 'bfloat16' (cast at stage "
                "time), or 'int8' (per-channel symmetric weight-only "
                "quantization, f32 scales, bf16 elsewhere)")
        elif sv.precision == "int8" and not self.registry.dir:
            # int8-requires-registry-staging: a quantized deployment must
            # serve gate-probed registry versions (the gate probes AT the
            # serving precision), never raw checkpoints with no
            # quality-gate lineage. `nvs3d serve` enforces the --registry
            # flag itself; this catches configs that disarm the registry
            # entirely.
            errors.append(
                "serve.precision='int8' requires registry staging "
                "(registry.dir must be set): quantized serving only "
                "deploys versions whose PSNR gate probed them at int8 "
                "(registry/gate.py), so quantization loss counts "
                "against registry.gate_margin_db")
        if sv.k_max < 0:
            errors.append(
                f"serve.k_max={sv.k_max} must be >= 0 (0 disables "
                "trajectory serving; > 0 sizes each ring slot's device-"
                "resident frame bank)")
        elif sv.k_max > 0 and sv.scheduler != "step":
            errors.append(
                f"serve.k_max={sv.k_max} requires serve.scheduler='step' "
                "— trajectory frames re-enter the stepper RING between "
                "denoise steps; the whole-request dispatcher has no ring "
                "for them to re-enter (set serve.scheduler='step' or "
                "serve.k_max=0)")
        if sv.cond_cache not in (True, False):
            errors.append(
                f"serve.cond_cache={sv.cond_cache!r} must be True or "
                "False (the admission-time conditioning cache is host "
                "orchestration, not a backend kernel — there is no "
                "'auto' tier)")
        elif sv.cond_cache and sv.scheduler != "step":
            errors.append(
                "serve.cond_cache=True requires serve.scheduler='step' "
                "— cached cond activations live on stepper ring slots; "
                "the whole-request dispatcher has no slot to pin them "
                "to (set serve.scheduler='step' or cond_cache=False)")
        if sv.max_frames < 1:
            errors.append(
                f"serve.max_frames={sv.max_frames} must be >= 1 (it "
                "bounds the poses per trajectory request)")
        if sv.drain_timeout_s < 0:
            errors.append(
                f"serve.drain_timeout_s={sv.drain_timeout_s} must be "
                ">= 0 (the in-flight budget of a graceful drain)")
        if sv.max_worker_restarts < 0:
            errors.append(
                f"serve.max_worker_restarts={sv.max_worker_restarts} "
                "must be >= 0 (0 disables supervised worker restarts)")
        if sv.worker_backoff_s < 0:
            errors.append(
                f"serve.worker_backoff_s={sv.worker_backoff_s} must be "
                ">= 0")
        if sv.anomaly_strikes < 1:
            errors.append(
                f"serve.anomaly_strikes={sv.anomaly_strikes} must be "
                ">= 1 (strikes before a non-finite ring row is evicted)")
        if sv.stop_timeout_s <= 0:
            errors.append(
                f"serve.stop_timeout_s={sv.stop_timeout_s} must be > 0 "
                "(stop()'s worker-join budget before the stall "
                "diagnosis)")
        bo = sv.brownout
        for fname in ("queue_soft", "queue_hard", "debt_soft",
                      "debt_hard", "k_cap", "max_frames_cap"):
            if getattr(bo, fname) < 0:
                errors.append(
                    f"serve.brownout.{fname}={getattr(bo, fname)} must "
                    "be >= 0 (0 disables that signal)")
        if (bo.queue_soft > 0 and bo.queue_hard > 0
                and bo.queue_hard < bo.queue_soft):
            errors.append(
                f"serve.brownout.queue_hard={bo.queue_hard} must be >= "
                f"queue_soft={bo.queue_soft} (shed only past degrade)")
        if (bo.debt_soft > 0 and bo.debt_hard > 0
                and bo.debt_hard < bo.debt_soft):
            errors.append(
                f"serve.brownout.debt_hard={bo.debt_hard} must be >= "
                f"debt_soft={bo.debt_soft} (shed only past degrade)")
        if bo.retry_after_s < 0:
            errors.append(
                f"serve.brownout.retry_after_s={bo.retry_after_s} must "
                "be >= 0")
        if bo.k_cap > 0 and sv.k_max > 0 and bo.k_cap > sv.k_max:
            errors.append(
                f"serve.brownout.k_cap={bo.k_cap} must be <= "
                f"serve.k_max={sv.k_max} (a degraded admission cannot "
                "widen the bank window)")
        slo = sv.slo
        try:
            from novel_view_synthesis_3d_tpu.obs.slo import parse_targets

            targets = parse_targets(slo.targets)
        except ValueError as e:
            targets = {}
            errors.append(str(e))
        if targets and any(v <= 0 for v in targets.values()):
            errors.append(
                f"serve.slo.targets={slo.targets!r}: latency budgets "
                "must be > 0 ms")
        if not (0.0 < slo.objective < 1.0):
            errors.append(
                f"serve.slo.objective={slo.objective} must be in (0, 1)")
        if slo.fast_window_s <= 0 or slo.slow_window_s < slo.fast_window_s:
            errors.append(
                f"serve.slo windows ({slo.fast_window_s}, "
                f"{slo.slow_window_s}) must satisfy 0 < fast <= slow")
        if slo.fast_burn <= 0 or slo.slow_burn <= 0:
            errors.append(
                f"serve.slo burn thresholds ({slo.fast_burn}, "
                f"{slo.slow_burn}) must be > 0")
        if sv.step_floor_ms < 0:
            errors.append(
                f"serve.step_floor_ms={sv.step_floor_ms} must be >= 0 "
                "(0 disables dispatch pacing)")
        rt = self.router
        for fname in ("health_poll_s", "health_ttl_s",
                      "deploy_drain_timeout_s", "deploy_probation_s",
                      "deploy_burn_max", "deploy_swap_timeout_s"):
            if getattr(rt, fname) <= 0:
                errors.append(
                    f"router.{fname}={getattr(rt, fname)} must be > 0")
        if rt.health_ttl_s < rt.health_poll_s:
            errors.append(
                f"router.health_ttl_s={rt.health_ttl_s} must be >= "
                f"router.health_poll_s={rt.health_poll_s} (a snapshot "
                "must outlive at least one poll period)")
        if rt.retry_budget < 0:
            errors.append(
                f"router.retry_budget={rt.retry_budget} must be >= 0 "
                "(0 = no failover, surface the first error)")
        if rt.saturation_sweeps < 1:
            errors.append(
                f"router.saturation_sweeps={rt.saturation_sweeps} must "
                "be >= 1 (full-fleet shed sweeps before FleetSaturated)")
        if rt.affinity_entries < 1:
            errors.append(
                f"router.affinity_entries={rt.affinity_entries} must be "
                ">= 1 (orbit sessions need at least one pin slot)")
        if self.obs.telemetry_max_mb < 0:
            errors.append(
                f"obs.telemetry_max_mb={self.obs.telemetry_max_mb} must "
                "be >= 0 (0 = unbounded)")
        sc = self.diffusion.stochastic_cond
        if sc not in (True, False):
            errors.append(
                f"diffusion.stochastic_cond={sc!r} must be True (draw a "
                "random frame-bank view per denoise step — the 3DiM "
                "protocol) or False (condition on the most recent bank "
                "frame; deterministic ablation mode)")
        fs = self.diffusion.fused_step
        if fs not in (True, False, "auto"):
            errors.append(
                f"diffusion.fused_step={fs!r} must be True, False, or "
                "'auto' (the fused Pallas denoise-step kernel, "
                "ops/fused_step.py; 'auto' = TPU backends only)")
        elif fs is True and self.diffusion.sampler == "dpm++":
            errors.append(
                "diffusion.fused_step=True requires sampler 'ddpm' or "
                "'ddim' — dpm++ 2M carries x̂₀ history across steps and "
                "cannot run as one fused step (use 'auto' to fuse where "
                "possible; the step scheduler's first-order dpm++ "
                "fallback still fuses)")
        for fname in ("use_serving_attention", "use_fused_epilogue"):
            fv = getattr(self.model, fname)
            if fv not in (True, False, "auto"):
                errors.append(
                    f"model.{fname}={fv!r} must be True, False, or "
                    "'auto' (Pallas serving kernel; 'auto' = TPU "
                    "backends only, interpret mode when forced True "
                    "off-TPU)")
        if (self.model.use_fused_epilogue is True
                and not self.model.groupnorm_per_frame):
            errors.append(
                "model.use_fused_epilogue=True requires "
                "model.groupnorm_per_frame=True — the epilogue kernel "
                "normalizes one (frame, H·W, C) slab per grid row; "
                "shared-stats GN spans frames and keeps the XLA path")
        rg = self.registry
        if rg.publish_every < 0:
            errors.append(
                f"registry.publish_every={rg.publish_every} must be >= 0 "
                "(0 = the trainer never publishes)")
        if rg.publish_every > 0 and not rg.dir:
            errors.append(
                "registry.publish_every is set but registry.dir is empty — "
                "there is nowhere to publish to")
        if not rg.channel or "/" in rg.channel or os.sep in rg.channel:
            errors.append(
                f"registry.channel={rg.channel!r} must be a non-empty name "
                "with no path separators (it becomes a pointer file under "
                "<registry.dir>/channels/)")
        if rg.poll_s <= 0:
            errors.append(
                f"registry.poll_s={rg.poll_s} must be > 0 (the serving "
                "reload watcher polls the subscribed channel)")
        if rg.gate_margin_db < 0:
            errors.append(
                f"registry.gate_margin_db={rg.gate_margin_db} must be >= 0")
        if rg.gate_sample_steps < 1:
            errors.append(
                f"registry.gate_sample_steps={rg.gate_sample_steps} must "
                "be >= 1")
        elif (rg.publish_every > 0
                and rg.gate_sample_steps > self.diffusion.timesteps):
            # Only enforced when the registry lane is armed: the default
            # gate ladder must not invalidate tiny-timesteps configs that
            # never touch the registry (sampling_schedule still errors
            # clearly if a CLI promote exceeds the ladder).
            errors.append(
                f"registry.gate_sample_steps={rg.gate_sample_steps} must "
                f"be <= diffusion.timesteps={self.diffusion.timesteps} "
                "when registry.publish_every is set")
        if rg.gate_batch < 1:
            errors.append(
                f"registry.gate_batch={rg.gate_batch} must be >= 1")
        if rg.gate_trajectory_frames < 0 or rg.gate_trajectory_frames == 1:
            errors.append(
                f"registry.gate_trajectory_frames="
                f"{rg.gate_trajectory_frames} must be 0 (single-frame "
                "gate only) or >= 2 (adjacent-frame consistency needs at "
                "least one frame pair)")
        if rg.keep < 1:
            errors.append(
                f"registry.keep={rg.keep} must be >= 1 (gc must retain at "
                "least the newest version)")
        dl = self.distill
        if dl.target_steps < 1:
            errors.append(
                f"distill.target_steps={dl.target_steps} must be >= 1")
        elif dl.start_steps < dl.target_steps:
            errors.append(
                f"distill.start_steps={dl.start_steps} must be >= "
                f"distill.target_steps={dl.target_steps}")
        else:
            ratio, rem = divmod(dl.start_steps, dl.target_steps)
            if rem or (ratio & (ratio - 1)) != 0:
                errors.append(
                    f"distill.start_steps={dl.start_steps} must be "
                    f"target_steps × a power of two (each round halves "
                    f"the step count; got target_steps={dl.target_steps})")
        # start_steps <= diffusion.timesteps is enforced at the point of
        # use (train/distill.run_distill): the default ladder must not
        # invalidate tiny-timesteps test configs that never distill.
        if dl.steps_per_round < 1:
            errors.append(
                f"distill.steps_per_round={dl.steps_per_round} must be "
                ">= 1")
        if dl.batch_size < 1:
            errors.append(f"distill.batch_size={dl.batch_size} must be >= 1")
        if dl.lr <= 0:
            errors.append(f"distill.lr={dl.lr} must be > 0")
        if dl.snr_clip < 1.0:
            errors.append(
                f"distill.snr_clip={dl.snr_clip} must be >= 1 (the "
                "truncated-SNR weight is clip(SNR, 1, snr_clip))")
        ob = self.obs
        if not 0 <= ob.metrics_port <= 65535:
            errors.append(
                f"obs.metrics_port={ob.metrics_port} must be in [0, 65535] "
                "(0 = endpoint off)")
        if ob.trace_max_events < 1:
            errors.append(
                f"obs.trace_max_events={ob.trace_max_events} must be >= 1")
        if ob.device_poll_s < 0:
            errors.append(
                f"obs.device_poll_s={ob.device_poll_s} must be >= 0 "
                "(0 disables the device-memory monitor)")
        xp = tuple(ob.xprof_steps)
        if len(xp) != 2 or any(int(v) < 0 for v in xp) or (
                xp != (0, 0) and xp[1] <= xp[0]):
            errors.append(
                f"obs.xprof_steps={ob.xprof_steps} must be (start, end) "
                "with 0 <= start < end, or (0, 0) for off")
        pf = ob.profile
        for fname in ("every_steps", "window_steps",
                      "serve_every_dispatches", "serve_window_dispatches"):
            if getattr(pf, fname) < 0:
                errors.append(
                    f"obs.profile.{fname}={getattr(pf, fname)} must be "
                    ">= 0 (0 disables)")
        if (pf.every_steps > 0 and pf.window_steps > 0
                and pf.window_steps >= pf.every_steps):
            errors.append(
                f"obs.profile.window_steps={pf.window_steps} must be < "
                f"every_steps={pf.every_steps} (a window must close "
                "before the next cadence)")
        if (pf.serve_every_dispatches > 0
                and pf.serve_window_dispatches > 0
                and pf.serve_window_dispatches >= pf.serve_every_dispatches):
            errors.append(
                f"obs.profile.serve_window_dispatches="
                f"{pf.serve_window_dispatches} must be < "
                f"serve_every_dispatches={pf.serve_every_dispatches}")
        for axis in ("model", "seq", "stages"):
            if getattr(self.mesh, axis) < 1:
                errors.append(f"mesh.{axis} must be >= 1")
        if self.mesh.data == 0 or self.mesh.data < -1:
            errors.append("mesh.data must be -1 (all remaining) or >= 1")
        if self.mesh.stages > 1:
            # Pipeline stages ride the 'model' axis (parallel/pipeline.py):
            # one stage per model-shard, so the axis size must match, and
            # the other uses of that axis (TP) — or of shard_map-managed
            # model partitioning (sequence parallel, fsdp) — can't coexist
            # with the stage placement.
            if self.mesh.model != self.mesh.stages:
                errors.append(
                    f"mesh.stages={self.mesh.stages} requires mesh.model="
                    f"{self.mesh.stages} (stages are placed one per "
                    f"'model' shard; got mesh.model={self.mesh.model})")
            if t.tp:
                errors.append(
                    "mesh.stages > 1 conflicts with train.tp=True — both "
                    "claim the 'model' axis")
            if t.fsdp:
                errors.append(
                    "mesh.stages > 1 conflicts with train.fsdp=True — the "
                    "pipelined step passes stage-sliced params through "
                    "shard_map and cannot compose with data-axis param "
                    "sharding (use train.update_sharding='zero' for the "
                    "optimizer-state memory win instead)")
            if m.sequence_parallel:
                errors.append(
                    "mesh.stages > 1 conflicts with "
                    "model.sequence_parallel=True — ring attention's "
                    "shard_map cannot nest inside the pipeline stage "
                    "shard_map")
            if self.mesh.seq != 1:
                errors.append(
                    f"mesh.stages={self.mesh.stages} requires mesh.seq=1 "
                    f"(got {self.mesh.seq})")
        if errors:
            raise ValueError("invalid config:\n  - " + "\n  - ".join(errors))
        return self

    # ------------------------------------------------------------------
    # Serialization + overrides
    # ------------------------------------------------------------------
    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), indent=2, **kw)

    @classmethod
    def from_dict(cls, d: dict) -> "Config":
        def build(tp, sub):
            fields = {f.name: f for f in dataclasses.fields(tp)}
            kwargs = {}
            for k, v in sub.items():
                if k not in fields:
                    raise KeyError(f"unknown config field {tp.__name__}.{k}")
                ftype = fields[k].type
                if isinstance(ftype, str):  # from __future__ annotations
                    ftype = globals().get(ftype, ftype)
                if dataclasses.is_dataclass(ftype) and isinstance(v, dict):
                    # Nested sub-config (e.g. TrainConfig.watchdog): rebuild
                    # the dataclass so dotted overrides round-trip through
                    # to_dict() without degrading the field to a plain dict.
                    v = build(ftype, v)
                elif isinstance(v, list):
                    v = tuple(v)
                kwargs[k] = v
            return tp(**kwargs)

        return cls(
            model=build(ModelConfig, d.get("model", {})),
            diffusion=build(DiffusionConfig, d.get("diffusion", {})),
            data=build(DataConfig, d.get("data", {})),
            train=build(TrainConfig, d.get("train", {})),
            mesh=build(MeshConfig, d.get("mesh", {})),
            serve=build(ServeConfig, d.get("serve", {})),
            obs=build(ObsConfig, d.get("obs", {})),
            registry=build(RegistryConfig, d.get("registry", {})),
            distill=build(DistillConfig, d.get("distill", {})),
        )

    @classmethod
    def from_json(cls, s: str) -> "Config":
        return cls.from_dict(json.loads(s))

    def override(self, **dotted: Any) -> "Config":
        """Override with dotted keys: cfg.override(**{'model.ch': 64})."""
        d = self.to_dict()
        for key, val in dotted.items():
            parts = key.split(".")
            node = d
            for p in parts[:-1]:
                node = node[p]
            if parts[-1] not in node:
                raise KeyError(f"unknown config field {key}")
            node[parts[-1]] = val
        return Config.from_dict(d)

    def apply_cli(self, argv: Sequence[str]) -> "Config":
        """Apply 'model.ch=64'-style CLI overrides.

        Values parse as JSON, plus the Python spellings True/False/None —
        otherwise `model.use_flash_attention=False` would silently arrive
        as the string 'False' (truthy!) and either crash later or flip the
        wrong way.
        """
        py_literals = {"True": True, "False": False, "None": None}
        overrides = {}
        for arg in argv:
            if "=" not in arg:
                raise ValueError(f"override must look like key=value: {arg!r}")
            k, v = arg.split("=", 1)
            if v in py_literals:
                overrides[k] = py_literals[v]
            else:
                try:
                    overrides[k] = json.loads(v)
                except json.JSONDecodeError:
                    overrides[k] = v  # bare string
        return self.override(**overrides)


# ----------------------------------------------------------------------
# Config ladder presets (BASELINE.json "configs")
# ----------------------------------------------------------------------
PRESET_NAMES = ("reference", "tiny64", "base128", "paper256", "pod64")


def get_preset(name: str) -> Config:
    """Presets for the BASELINE.json config ladder.

    - 'reference': exact reference defaults incl. its behavior quirks
      (shared-frame GroupNorm stats, Frobenius loss) for parity checks.
    - 'tiny64':   XUnet-tiny 64px (single-host smoke; ref defaults, sane flags)
    - 'base128':  XUnet-base 128px, ch=128, ch_mult=(1,2,2,4)
    - 'paper256': 3DiM paper config 256px, ch=256, ch_mult=(1,2,2,4,4)
    """
    if name == "reference":
        return Config(
            # Pin the XLA attention path too: this preset exists for parity
            # checks against the reference, and the fused kernel matches it
            # only approximately on TPU.
            model=ModelConfig(groupnorm_per_frame=False,
                              use_flash_attention=False),
            train=TrainConfig(loss="frobenius"),
        )
    if name == "tiny64":
        return Config()
    if name == "base128":
        return Config(
            model=ModelConfig(ch=128, ch_mult=(1, 2, 2, 4), emb_ch=512,
                              dtype="bfloat16"),
            data=DataConfig(img_sidelength=128),
            train=TrainConfig(batch_size=8, ema_decay=0.9999),
            diffusion=DiffusionConfig(sample_timesteps=256),
        )
    if name == "paper256":
        return Config(
            model=ModelConfig(ch=256, ch_mult=(1, 2, 2, 4, 4), emb_ch=1024,
                              num_res_blocks=3, dtype="bfloat16", remat=True),
            data=DataConfig(img_sidelength=256),
            # Measured on v5e (results/tpu_r04/analyze_paper256.out): the
            # 708M-param state is params f32 2.64G + Adam nu f32 2.64G +
            # mu bf16 1.32G, and a DEVICE EMA copy (f32 2.64G) pushed total
            # usage to 17.94G of 15.75G — OOM. ema_host moves that copy to
            # host RAM (bf16 EMA would be wrong: decay 0.9999 updates round
            # to nothing in 8 mantissa bits). grad_accum: the batch-8 256px
            # step wants ~32G of activations; micro-batches of 1 with
            # remat fit. On an N-chip mesh the effective accumulation
            # shrinks automatically (per-chip memory scales as 1/N).
            train=TrainConfig(batch_size=8, ema_decay=0.9999,
                              ema_host=True,
                              grad_accum_steps=8,
                              # 0.5x param bytes of HBM back on the 16G
                              # chip; see TrainConfig.adam_mu_dtype.
                              adam_mu_dtype="bfloat16",
                              # In-loop probes pin the EMA copy on-chip;
                              # f32 would be 2.6G the margin doesn't have
                              # (see TrainConfig.probe_dtype).
                              probe_dtype="bfloat16"),
            diffusion=DiffusionConfig(sample_timesteps=256),
        )
    if name == "pod64":
        # BASELINE ladder step 5: v5e-64 pod-scale DP pretrain of the
        # paper256 model (derived from that preset so the model can't
        # drift). 'data=-1' absorbs all chips of the slice; each of the
        # pod's hosts feeds its local shard (Grain/native loader per
        # process); FSDP shards params+Adam state so the 256-ch UNet leaves
        # HBM room for batch; run with NVS3D_MULTIHOST=1 (parallel/dist.py).
        return get_preset("paper256").override(**{
            "data.num_workers": 16,
            "data.prefetch": 8,
            "train.batch_size": 256,
            "train.fsdp": True,
            # Per-chip batch is already small on 64 chips (256/64 = 4) and
            # FSDP frees the param/optimizer HBM — no micro-batching needed.
            "train.grad_accum_steps": 1,
            # FSDP shards the EMA copy too (2.64G/64 per chip) — keep it
            # on device; the host-EMA path would all-gather params on every
            # update across the pod instead.
            "train.ema_host": False,
        })
    raise KeyError(f"unknown preset {name!r}")
